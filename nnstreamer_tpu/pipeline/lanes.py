"""Parallel ingest lanes — ordered multi-worker ingest.

Everything upstream of a pipeline's first ``queue`` runs on ONE streaming
thread (the source's ``run_loop`` drives the chain as plain calls). After
the dispatch window (PR 3) and the device-resident plane (PR 4), that
serial host segment — frame acquisition → ``tensor_converter`` →
host-side ``tensor_transform`` — is the flagship bench's dominant
bottleneck (``ingest_bound_fps`` 486 vs a ~1798 fps device ceiling).
NNStreamer's answer to the same problem is multiple streaming threads per
pipeline (arxiv 1901.04985); ours replicates the *replicable* part of the
pre-queue segment across N worker lanes while keeping the stream order
contract exact:

- The source keeps its single ``create()`` loop (acquisition is cheap and
  inherently ordered); every frame is stamped with a **monotone sequence
  number** at the executor's sink pad and round-robined to a lane.
- Each lane owns private **clones** of the segment elements (same type,
  same properties) so no per-frame state is ever shared, plus a private
  :func:`~nnstreamer_tpu.tensors.pool.get_lane_pool` arena: the first
  thing a lane does is copy the frame into a pooled staging slab —
  GIL-releasing ``memcpy`` work that parallelizes even when the
  downstream math was folded on-device.
- Outputs reassemble through a **bounded reorder buffer**; a single drain
  pushes them downstream strictly in sequence order, so the bytes, the
  order, and the EOS drain are identical to the serial path.

Which elements replicate is decided by :meth:`Element.reorder_safe`
(class flag ``REORDER_SAFE``, audited statically by lint rule NNS109):
the walk from the source stops at the first stateful / multi-pad element,
queue, or fused region. Ordering after fusion is the **device-side
preprocessing preamble**: a ``tensor_transform`` adjacent to a filter has
already been folded into the region's jitted program by ``fuse_pipeline``
by the time lanes plan, so lane workers spend their time in numpy/copy
code and the cast/normalize math rides the region's one XLA dispatch.

``lanes=1`` (or the ``NNSTPU_LANES=1`` kill switch) leaves the pipeline
untouched — the exact serial code path. Observability:
``nns_lane_occupancy`` (busy lanes), ``nns_lane_reorder_stall_seconds``
(worker time blocked on a full reorder buffer — head-of-line pressure),
and ``nns_ingest_fps`` (frames forwarded downstream per second), all in
``Pipeline.metrics_snapshot()`` under ``lanes`` and on ``/metrics``.
"""

from __future__ import annotations

import collections
import os
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.pipeline.element import (
    CapsEvent,
    Element,
    EosEvent,
    Event,
    FlowError,
    FlowReturn,
    Pad,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("lanes")

#: sequence stamp carried in buffer meta (observability; the reorder
#: machinery itself threads (seq, buf) pairs explicitly)
LANE_SEQ_META = "lane_seq"

#: how long a serialized EOS may wait for the reorder drain (mirrors
#: Queue's serialized-EOS timeout)
_EOS_DRAIN_TIMEOUT_S = 30.0


def lanes_override() -> Optional[int]:
    """The ``NNSTPU_LANES`` env override: ``1`` is the kill switch that
    restores the serial path regardless of configuration, higher values
    force that lane count. Unset/invalid → None (use the configured
    value)."""
    raw = os.environ.get("NNSTPU_LANES", "").strip()
    if not raw:
        return None
    try:
        return max(1, int(raw))
    except ValueError:
        log.warning("NNSTPU_LANES=%r is not an int; ignoring", raw)
        return None


def effective_lanes(requested: int) -> int:
    """The lane count a pipeline actually runs: env override first, then
    the pipeline's configured ``lanes``."""
    env = lanes_override()
    if env is not None:
        return env
    try:
        return max(1, int(requested or 1))
    except (TypeError, ValueError):
        return 1


def _single_io(el: Element) -> bool:
    return len(el.sinkpads) == 1 and len(el.srcpads) == 1


def _tl_seq(items: List[Tuple[str, Any]]) -> Optional[int]:
    """Trace context of a reorder slot: the first buffer's stamped
    frame-ledger seq (obs/timeline.py); event-only slots have none."""
    for kind, payload in items:
        if kind == "buf":
            return payload.meta.get(_timeline.TRACE_SEQ_META)
    return None


class _LaneTail(Element):
    """Terminal collector of one lane's clone chain: records everything
    the segment emits (buffers AND events, in emission order) so the
    worker can hand the frame's complete output to the reorder buffer as
    one ordered unit."""

    ELEMENT_NAME = "lane_tail"
    HANDLES_DEFERRED = True   # never force a deferred finalize
    DEVICE_PASSTHROUGH = True  # never materialize a resident payload

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        #: ("buf", TensorBuffer) | ("event", Event), single-threaded per
        #: lane (only that lane's worker — or the negotiating source
        #: thread, never both at once — drives this chain)
        self.items: List[Tuple[str, Any]] = []

    def chain(self, pad, buf):
        self.items.append(("buf", buf))
        return FlowReturn.OK

    def sink_event(self, pad, event):
        self.items.append(("event", event))

    def take(self) -> List[Tuple[str, Any]]:
        out, self.items = self.items, []
        return out


class IngestLanes(Element):
    """The lane executor, spliced between a source and its replicable
    segment's downstream peer (same splice mechanics as
    :class:`~nnstreamer_tpu.pipeline.fuse.FusedRegion`). The original
    segment elements stay in the pipeline but no buffers flow through
    them; per-lane clones do the work."""

    ELEMENT_NAME = "ingest_lanes"
    HANDLES_DEFERRED = True
    DEVICE_PASSTHROUGH = True
    PROPERTIES = {**Element.PROPERTIES,
                  #: reorder-buffer capacity in frames ahead of the next
                  #: in-order sequence; 0 = auto (2× lane count, min 8)
                  "reorder_capacity": 0}

    def __init__(self, source: Element, segment: List[Element],
                 lanes: int, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self.source = source
        self.segment: List[Element] = list(segment)
        self.n = max(2, int(lanes))
        # lane machinery (built per start(): a restart picks up property
        # edits on the originals and starts from clean clone state)
        self._heads: List[Element] = []
        self._tails: List[_LaneTail] = []
        self._clones: List[List[Element]] = []
        self._lane_qs: List[_queue.Queue] = []
        self._pools: List[Any] = []
        self._stage_win: dict = {}
        self._busy: List[bool] = []
        self._workers: List[threading.Thread] = []
        self._drainer: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # sequence / reorder state — _seq is written only by the single
        # upstream streaming thread; _pending/_next under _cv
        self._seq = 0
        self._next = 0
        #: slots fully pushed downstream (bumped AFTER _forward returns —
        #: _next alone would let a serialized EOS overtake the final
        #: frame, which the drain thread pops before it pushes)
        self._delivered = 0
        self._pending: Dict[int, List[Tuple[str, Any]]] = {}
        #: reorder-buffer entry stamps for the frame ledger (tracing
        #: only; keyed like _pending, maintained under _cv)
        self._pending_t: Dict[int, float] = {}
        self._cv = threading.Condition()
        self._forwarded = 0
        self._fwd_times: collections.deque = collections.deque(maxlen=256)
        self._last_caps_str: Optional[str] = None
        #: last negotiated caps — replayed through a restarted lane's
        #: fresh clone chain so it is negotiated like its predecessor
        self._saved_caps = None
        self._m_stall = None  # lazy: labels need the owning pipeline
        self._m_leaked = None
        self._m_restarts = None

    # -- capacity ------------------------------------------------------------
    def _capacity(self) -> int:
        cap = int(self.get_property("reorder_capacity") or 0)
        return cap if cap > 0 else max(8, 2 * self.n)

    # -- obs -----------------------------------------------------------------
    def _obs_init(self) -> None:
        import weakref

        reg = get_registry()
        labels = self._obs_labels()
        self._m_stall = reg.counter(
            "nns_lane_reorder_stall_seconds",
            "Cumulative lane-worker time blocked on a full reorder "
            "buffer (head-of-line pressure from a slow lane)", **labels)
        self._m_leaked = reg.counter(
            "nns_lane_leaked_threads_total",
            "Lane executor threads that failed to join within the "
            "stop() timeout (leaked past teardown)", **labels)
        self._m_restarts = reg.counter(
            "nns_fault_lane_restarts_total",
            "Lane worker clone-chain restarts under supervision",
            **labels)
        ref = weakref.ref(self)
        reg.gauge(
            "nns_lane_occupancy",
            "Lane workers currently processing a frame",
            fn=lambda: (sum(ref()._busy) if ref() is not None else 0),
            **labels)
        reg.gauge(
            "nns_ingest_fps",
            "Frames the lane executor forwarded downstream per second "
            "(recent window)",
            fn=lambda: (ref()._ingest_fps() if ref() is not None else 0.0),
            **labels)

    def _ingest_fps(self) -> float:
        times = list(self._fwd_times)
        if len(times) < 2:
            return 0.0
        span = times[-1] - times[0]
        return (len(times) - 1) / span if span > 0 else 0.0

    def obs_snapshot(self):
        out = super().obs_snapshot()
        with self._cv:
            reorder_depth = len(self._pending)
        out.update({
            "lanes": self.n,
            "occupancy": sum(self._busy),
            "reorder_depth": reorder_depth,
            "reorder_capacity": self._capacity(),
            "forwarded": self._forwarded,
            "ingest_fps": round(self._ingest_fps(), 2),
        })
        if self._m_stall is not None:
            out["reorder_stall_s"] = round(self._m_stall.value, 4)
        return out

    # -- lane construction ---------------------------------------------------
    def _clone_of(self, el: Element, lane: int) -> Element:
        props = {k: v for k, v in el._props.items() if k != "name"}
        clone = type(el)(name=f"{el.name}~l{lane}", **props)
        clone.pipeline = self.pipeline  # metric labels / error context
        return clone

    def _build_lanes(self) -> None:
        from nnstreamer_tpu.tensors.pool import get_lane_pool, pool_enabled

        self._heads, self._tails, self._clones = [], [], []
        self._lane_qs, self._pools = [], []
        #: per-lane rolling staging windows (id(pool) → window state);
        #: single-writer per entry — only that lane's worker
        self._stage_win = {}
        self._busy = [False] * self.n
        for k in range(self.n):
            clones = [self._clone_of(el, k) for el in self.segment]
            tail = _LaneTail(name=f"{self.name}~tail{k}")
            tail.pipeline = self.pipeline
            for a, b in zip(clones, clones[1:]):
                a.srcpads[0].link(b.sinkpads[0])
            clones[-1].srcpads[0].link(tail.sinkpads[0])
            for c in clones:
                c.start()
            self._clones.append(clones)
            self._heads.append(clones[0])
            self._tails.append(tail)
            # small per-lane feed queue: enough to keep the lane busy,
            # small enough that backpressure reaches the source promptly
            self._lane_qs.append(_queue.Queue(maxsize=4))
            self._pools.append(get_lane_pool(k) if pool_enabled() else None)

    # -- state ---------------------------------------------------------------
    def start(self):
        super().start()
        self._stop_evt.clear()
        self._seq = 0
        self._next = 0
        self._delivered = 0
        self._pending = {}
        self._pending_t = {}
        self._forwarded = 0
        self._fwd_times.clear()
        self._last_caps_str = None
        self._build_lanes()
        if self._m_stall is None:
            self._obs_init()
        self._workers = []
        for k in range(self.n):
            t = threading.Thread(target=self._worker, args=(k,),
                                 name=f"{self.name}-lane{k}", daemon=True)
            self._workers.append(t)
            t.start()
        self._drainer = threading.Thread(target=self._drain_loop,
                                         name=f"{self.name}-drain",
                                         daemon=True)
        self._drainer.start()

    def stop(self):
        self._stop_evt.set()
        with self._cv:
            self._cv.notify_all()
        for k, t in enumerate(self._workers):
            t.join(timeout=5)
            if t.is_alive():
                self._count_leaked(f"lane {k} worker", t)
        self._workers = []
        if self._drainer is not None:
            self._drainer.join(timeout=5)
            if self._drainer.is_alive():
                self._count_leaked("drain thread", self._drainer)
            self._drainer = None
        for clones in self._clones:
            for c in clones:
                c.stop()
        super().stop()

    def _count_leaked(self, what: str, thread: threading.Thread) -> None:
        """A join timed out: the thread outlives the executor. Silent
        before — now every leak is logged with its identity and counted
        (``nns_lane_leaked_threads_total``) so teardown hangs show up in
        tests and on dashboards instead of as mystery threads."""
        self.log.warning(
            "%s: %s (%s) did not join within 5s at stop(); thread leaked",
            self.name, what, thread.name)
        if self._m_leaked is not None:
            self._m_leaked.inc()

    # -- splicing ------------------------------------------------------------
    def splice(self, pipe) -> None:
        self.pipeline = pipe
        first, last = self.segment[0], self.segment[-1]
        up_src = first.sinkpads[0].peer  # the source's src pad
        down_sink = last.srcpads[0].peer
        if up_src is not None:
            up_src.unlink()
            up_src.link(self.sinkpad)
        if down_sink is not None:
            last.srcpads[0].unlink()
            self.srcpad.link(down_sink)
        log.info("ingest lanes: %s (%d lanes over [%s])", self.name,
                 self.n, "+".join(el.name for el in self.segment))

    # -- hot path ------------------------------------------------------------
    def chain(self, pad, buf):
        seq = self._seq
        self._seq = seq + 1
        buf.meta[LANE_SEQ_META] = seq
        q = self._lane_qs[seq % self.n]
        while not self._stop_evt.is_set():
            try:
                q.put((seq, buf), timeout=0.1)
                return FlowReturn.OK
            except _queue.Full:
                continue
        return FlowReturn.EOS

    #: frames staged per rolling window slab (per lane, per tensor): the
    #: lane writes successive frames into successive SLOTS of one
    #: contiguous pool slab instead of per-frame staging buffers, so a
    #: downstream batched upload (``tensors/buffer.py`` ``upload_many``)
    #: re-wraps a drained run as the stacked H2D view with zero extra
    #: host copies (``pool.contiguous_window_view``)
    STAGE_WINDOW_FRAMES = 8

    def _stage_copy(self, buf: TensorBuffer, pool) -> TensorBuffer:
        """Copy host payloads into this lane's private pool arena: the
        GIL-releasing memcpy that makes lane parallelism real even when
        the per-frame math was folded on-device, and the reason a source
        frame (possibly a shared cached array or another pool's slab)
        never couples lanes through slab refcounts.

        Frames land in consecutive slots of a rolling window slab
        (single-writer: only this lane's worker touches its window
        state). A signature change or a full window rolls to a fresh
        slab; old slabs stay alive through their live slot views (the
        pool's refcount guard) and fall to GC when the last reader
        drops."""
        if pool is None or not buf.tensors:
            return buf
        if not all(isinstance(t, np.ndarray) for t in buf.tensors):
            return buf  # resident payloads stage nothing on the host
        sig = tuple((t.shape, t.dtype) for t in buf.tensors)
        wins = self._stage_win
        st = wins.get(id(pool))
        if st is None or st["sig"] != sig or \
                st["next"] >= self.STAGE_WINDOW_FRAMES:
            st = {"sig": sig, "next": 0,
                  "slabs": [pool.acquire_window(self.STAGE_WINDOW_FRAMES,
                                                t.shape, t.dtype)
                            for t in buf.tensors]}
            wins[id(pool)] = st
        i = st["next"]
        st["next"] = i + 1
        staged = []
        for t, win in zip(buf.tensors, st["slabs"]):
            np.copyto(win[i], t)
            staged.append(win[i])
        return buf.with_tensors(staged)

    def _worker(self, k: int) -> None:
        q, pool = self._lane_qs[k], self._pools[k]
        while not self._stop_evt.is_set():
            try:
                seq, buf = q.get(timeout=0.1)
            except _queue.Empty:
                continue
            self._busy[k] = True
            tl = _timeline.ACTIVE
            t_pick = time.monotonic() if tl is not None else 0.0
            try:
                fi = _faults.ACTIVE
                if fi is not None:
                    # chaos hook: kind=crash simulates abrupt worker
                    # death — supervision (below) restarts the lane
                    fi.check("lane.worker",
                             seq=buf.meta.get(_timeline.TRACE_SEQ_META))
                # re-read per iteration: supervision may have swapped in
                # a fresh clone chain after a restart
                head = self._heads[k]
                head._chain_entry(head.sinkpads[0],
                                  self._stage_copy(buf, pool))
                items = self._tails[k].take()
            except Exception as e:  # noqa: BLE001 — a lane failure must
                # reach the bus (halt) or lane supervision (any other
                # error policy), never die silently
                self._busy[k] = False
                if self._halt_policy():
                    self.post_error(
                        e if isinstance(e, FlowError)
                        else FlowError(f"{self.name}: lane {k}: {e}"))
                    self._stop_evt.set()
                    with self._cv:
                        self._cv.notify_all()
                    return
                self._supervise_lane_failure(k, seq, buf, e)
                continue
            self._busy[k] = False
            if tl is not None:
                # recorded from the lane worker's own thread, so the
                # export shows each lane as its own track (lanes as
                # threads); not part of the reconciliation tiling — it
                # overlaps the frame's ingest window
                tl.span("lane_exec",
                        buf.meta.get(_timeline.TRACE_SEQ_META),
                        t_pick, time.monotonic(), lane=k)
            self._reorder_put(seq, items)

    def _reorder_put(self, seq: int, items: List[Tuple[str, Any]]) -> None:
        cap = self._capacity()
        t0 = None
        with self._cv:
            while seq - self._next >= cap and not self._stop_evt.is_set():
                if t0 is None:
                    t0 = time.monotonic()
                self._cv.wait(timeout=0.1)
            if t0 is not None and self._m_stall is not None:
                self._m_stall.inc(time.monotonic() - t0)
            self._pending[seq] = items
            tl = _timeline.ACTIVE
            if tl is not None:
                now = time.monotonic()
                self._pending_t[seq] = now
                if t0 is not None:
                    tl.span("lane_stall", _tl_seq(items), t0, now)
            self._cv.notify_all()

    # -- lane supervision (pipeline/supervise.py policies) -------------------
    def _supervise_lane_failure(self, k: int, seq: int, buf,
                                exc: BaseException) -> None:
        """A lane worker failed with a non-halt error policy: restart
        the lane's clone chain (its per-frame state is untrusted after
        an arbitrary failure), then either replay the in-flight frame
        through the fresh chain (``retry``/``degrade``) or account it as
        dropped (``skip-frame``). Either way the frame's sequence slot
        is filled — a real result or an empty tombstone — so the reorder
        buffer delivers every surviving frame in order, byte-identical
        to a run where the dead frame never existed."""
        from nnstreamer_tpu.pipeline import supervise

        policy = supervise.effective_policy(self)
        if self._m_restarts is not None:
            self._m_restarts.inc()
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("lane_restart",
                    buf.meta.get(_timeline.TRACE_SEQ_META),
                    track="faults", lane=k)
        self.log.warning(
            "%s: lane %d worker failed on seq %d (%s); restarting clone "
            "chain under error-policy=%s", self.name, k, seq, exc, policy)
        try:
            self._rebuild_lane(k)
        except Exception as e:  # noqa: BLE001 — a lane that cannot be
            # rebuilt is unrecoverable; fail the pipeline
            self.post_error(FlowError(
                f"{self.name}: lane {k} restart failed: {e}"))
            self._stop_evt.set()
            with self._cv:
                self._cv.notify_all()
            return
        m = supervise._metrics(self)
        if policy == "skip-frame":
            self._tombstone(k, seq, buf, exc, m)
            return
        # retry / degrade: replay the in-flight frame through the fresh
        # chain with the element-standard bounded backoff
        retry_max = max(1, int(self._props.get("retry_max") or 3))
        base_ms = float(self._props.get("retry_backoff_ms") or 5.0)
        pool = self._pools[k]
        last = exc
        for attempt in range(1, retry_max + 1):
            supervise._backoff_sleep(self, attempt, base_ms)
            m["retries"].inc()
            try:
                head = self._heads[k]
                head._chain_entry(head.sinkpads[0],
                                  self._stage_copy(buf, pool))
                items = self._tails[k].take()
            except Exception as e:  # noqa: BLE001 — bounded ladder; the
                # frame is tombstoned below when attempts run out
                last = e
                continue
            m["recovered"].inc()
            self.log.warning(
                "%s: lane %d recovered seq %d on retry %d/%d", self.name,
                k, seq, attempt, retry_max)
            self._reorder_put(seq, items)
            return
        self._tombstone(k, seq, buf, last, m)

    def _tombstone(self, k: int, seq: int, buf, exc: BaseException,
                   m) -> None:
        """Fill the dead frame's sequence slot with an empty unit: the
        drain advances past it delivering nothing, so survivors stay in
        order and the EOS drain still completes."""
        m["skipped"].inc()
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("fault_skip", buf.meta.get(_timeline.TRACE_SEQ_META),
                    track="faults", element=self.name, lane=k)
        self.log.warning("%s: lane %d dropped seq %d after failure (%s)",
                         self.name, k, seq, exc)
        self._reorder_put(seq, [])

    def _rebuild_lane(self, k: int) -> None:
        """Swap lane k's clone chain for a fresh one. Single-writer
        safe: only worker k drives lane k's chain, and the caps
        renegotiation barrier waits for every stamped slot (including
        the in-flight one this rebuild is filling) before touching
        heads."""
        for c in self._clones[k]:
            try:
                c.stop()
            except Exception as e:  # noqa: BLE001 — the dead chain's
                # teardown must not block its replacement
                self.log.warning("%s: lane %d clone %s stop failed: %s",
                                 self.name, k, c.name, e)
        clones = [self._clone_of(el, k) for el in self.segment]
        tail = _LaneTail(name=f"{self.name}~tail{k}")
        tail.pipeline = self.pipeline
        for a, b in zip(clones, clones[1:]):
            a.srcpads[0].link(b.sinkpads[0])
        clones[-1].srcpads[0].link(tail.sinkpads[0])
        for c in clones:
            c.start()
        self._clones[k] = clones
        self._heads[k] = clones[0]
        self._tails[k] = tail
        if self._saved_caps is not None:
            head = clones[0]
            head._event_entry(head.sinkpads[0],
                              CapsEvent(self._saved_caps))
            tail.take()  # announcement already forwarded by lane 0

    def _drain_loop(self) -> None:
        while not self._stop_evt.is_set():
            with self._cv:
                items = self._pending.pop(self._next, None)
                if items is None:
                    self._cv.wait(timeout=0.1)
                    continue
                t_in = self._pending_t.pop(self._next, None)
                self._next += 1
                self._cv.notify_all()
            tl = _timeline.ACTIVE
            if tl is not None and t_in is not None:
                # the frame's park time in the reorder buffer — a
                # critical-path stage; the first downstream queue
                # subtracts it from the ingest span so the two tile
                now = time.monotonic()
                tl.span("lane_reorder", _tl_seq(items), t_in, now,
                        track="reorder")
                for kind, payload in items:
                    if kind == "buf":
                        payload.meta["tl_reorder_s"] = now - t_in
                        break
            try:
                self._forward(items)
                with self._cv:
                    self._delivered += 1
                    self._cv.notify_all()
            except Exception as e:  # noqa: BLE001 — downstream failures
                # must reach the bus, not silently kill the drain thread
                self.post_error(e if isinstance(e, FlowError)
                                else FlowError(f"{self.name}: {e}"))
                self._stop_evt.set()
                with self._cv:
                    self._cv.notify_all()
                return

    def _forward(self, items: List[Tuple[str, Any]]) -> None:
        """Push one sequence slot's output downstream, in emission order.
        Single consumer (the drain thread, or the streaming thread during
        negotiation when no frames are in flight) — downstream elements
        see exactly one pushing thread, like the serial path."""
        for kind, payload in items:
            if kind == "buf":
                self._forwarded += 1
                self._fwd_times.append(time.monotonic())
                self.srcpad.push(payload)
            else:
                if isinstance(payload, CapsEvent):
                    # every lane announces the same lazily-derived caps;
                    # the serial path announces once — dedupe to match
                    key = str(payload.caps)
                    if key == self._last_caps_str:
                        continue
                    self._last_caps_str = key
                self.srcpad.push_event(payload)

    # -- events --------------------------------------------------------------
    def _wait_drained(self, target: int, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._delivered < target and not self._stop_evt.is_set():
                if time.monotonic() >= deadline:
                    return False
                self._cv.wait(timeout=0.1)
        return True

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            # (re)negotiation is a barrier: flush in-flight frames, then
            # run the caps through every lane's clone chain so each is
            # negotiated; forward lane 0's announcement (all identical)
            self._saved_caps = event.caps
            if not self._wait_drained(self._seq,
                                      timeout=_EOS_DRAIN_TIMEOUT_S):
                # satellite fix: this False was silently dropped — the
                # barrier proceeding with frames still in flight means
                # those frames render under the WRONG caps downstream
                self.post_warning(
                    f"caps renegotiation barrier timed out after "
                    f"{_EOS_DRAIN_TIMEOUT_S:.0f}s with "
                    f"{self._seq - self._delivered} frame slot(s) "
                    f"undelivered; proceeding — in-flight frames may "
                    f"carry stale caps")
            first_items: List[Tuple[str, Any]] = []
            for k in range(self.n):
                head = self._heads[k]
                head._event_entry(head.sinkpads[0], CapsEvent(event.caps))
                items = self._tails[k].take()
                if k == 0:
                    first_items = items
            self._forward(first_items)
            return
        if isinstance(event, EosEvent):
            # serialized EOS: every stamped frame drains through the
            # reorder buffer before EOS crosses downstream
            if not self._wait_drained(self._seq,
                                      timeout=_EOS_DRAIN_TIMEOUT_S):
                # satellite fix: a swallowed timeout here silently
                # dropped the undrained frames — put the loss on the bus
                # where applications (and the chaos tests) can see it
                self.post_warning(
                    f"EOS drain timed out after "
                    f"{_EOS_DRAIN_TIMEOUT_S:.0f}s with "
                    f"{self._seq - self._delivered} frame slot(s) "
                    f"undelivered; those frames are lost")
            self.srcpad.push_event(event)
            return
        # any other serialized event: give it a sequence slot so it never
        # overtakes (or falls behind) the frames around it
        seq = self._seq
        self._seq = seq + 1
        self._reorder_put(seq, [("event", event)])

    def __repr__(self):
        names = "+".join(el.name for el in self.segment)
        return f"<IngestLanes {self.name!r} n={self.n} over [{names}]>"


# --------------------------------------------------------------------------
# planning
# --------------------------------------------------------------------------
def plan_lane_segments(pipe) -> List[Tuple[Element, List[Element]]]:
    """Find, per eligible source, the maximal replicable pre-queue
    segment: single-src-pad REORDER_SAFE source, then the downstream run
    of single-io ``reorder_safe()`` elements, stopping at the first
    queue, fused region, multi-pad, or stateful element. Runs after
    ``fuse_pipeline`` so a transform folded into a region (the
    device-side preprocessing preamble) is already out of the segment."""
    from nnstreamer_tpu.pipeline.fuse import FusedRegion, device_foldable
    from nnstreamer_tpu.pipeline.pipeline import Queue, SourceElement

    plans: List[Tuple[Element, List[Element]]] = []
    for src in pipe.elements:
        if not isinstance(src, SourceElement):
            continue
        if len(src.srcpads) != 1 or not src.reorder_safe():
            continue
        segment: List[Element] = []
        peer = src.srcpads[0].peer
        cur = peer.element if peer is not None else None
        while (cur is not None and _single_io(cur)
               and not isinstance(cur, (Queue, SourceElement, FusedRegion))
               and getattr(cur, "_fused_region", None) is None
               and cur.reorder_safe()):
            segment.append(cur)
            nxt = cur.srcpads[0].peer
            cur = nxt.element if nxt is not None else None
        if not segment:
            continue
        if isinstance(cur, FusedRegion):
            log.info("lane segment for %s ends at %s — preprocessing "
                     "runs device-side inside the fused region", src.name,
                     cur.name)
        elif cur is not None and device_foldable(cur):
            log.info("lane segment for %s ends at stage-capable %s left "
                     "host-side (enable NNSTPU_FUSE to fold it on-device)",
                     src.name, cur.name)
        plans.append((src, segment))
    return plans


def splice_lanes(pipe, lanes: int) -> List[IngestLanes]:
    """Splice an :class:`IngestLanes` executor behind every source with a
    replicable segment. ``lanes <= 1`` is the serial path: nothing is
    planned, nothing is touched."""
    if lanes <= 1:
        return []
    execs: List[IngestLanes] = []
    for src, segment in plan_lane_segments(pipe):
        ex = IngestLanes(src, segment, lanes, name=f"{src.name}-lanes")
        ex.splice(pipe)
        execs.append(ex)
    return execs
