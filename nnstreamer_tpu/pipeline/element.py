"""Element / Pad / Event — the dataflow substrate.

The reference builds on GStreamer's element model: elements expose src/sink
pads; buffers flow downstream through per-pad ``chain`` functions; events
(CAPS, EOS, custom like RELOAD_MODEL) flow alongside; caps negotiation fixes
stream formats at link/first-buffer time. We keep exactly that capability —
it is what makes 20+ semantics-agnostic elements composable — with a design
chosen for the TPU runtime:

- **Synchronous push by default.** A source thread drives its whole chain of
  elements as plain function calls, so a ``jax.Array`` produced by one
  element is consumed by the next with zero host round-trips and zero queue
  latency. XLA's async dispatch already pipelines device work; host-side
  threads per element (GStreamer's model) would only add latency.
- **Explicit thread boundaries.** A ``queue`` element introduces a bounded
  ring buffer + worker thread where stage decoupling is wanted (reference:
  gst ``queue``); multi-input elements (mux/merge/join) are natural thread
  joins and do their own locking.
- **Events carry negotiation.** ``CapsEvent`` fixes per-pad
  ``TensorsConfig``-bearing caps before the first buffer; elements override
  hooks rather than reimplementing negotiation.

Flow control mirrors GstFlowReturn: ``FlowReturn.OK/EOS``, errors raise
:class:`FlowError` (carried to the pipeline bus by the driving thread).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList
from nnstreamer_tpu.tensors.buffer import (
    H2D_EXCLUSIVE_META,
    DeviceBuffer,
    TensorBuffer,
    record_residency_entry,
)
from nnstreamer_tpu.utils.stats import InvokeStats


class FlowReturn(enum.Enum):
    OK = "ok"
    EOS = "eos"


class FlowError(RuntimeError):
    """Fatal streaming error (GST_FLOW_ERROR equivalent)."""


class PadDirection(enum.Enum):
    SRC = "src"
    SINK = "sink"


# --------------------------------------------------------------------------
# Events
# --------------------------------------------------------------------------
@dataclasses.dataclass
class Event:
    """Base event; flows downstream through pads."""


@dataclasses.dataclass
class CapsEvent(Event):
    caps: Caps


@dataclasses.dataclass
class EosEvent(Event):
    pass


@dataclasses.dataclass
class CustomEvent(Event):
    """Named application event (reference custom downstream events, e.g.
    RELOAD_MODEL on tensor_filter)."""

    name: str
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class QosEvent(Event):
    """Throttling QoS — flows *upstream* (reference GST_EVENT_QOS, posted
    by tensor_rate with throttle=true so upstream inference skips frames
    that would be dropped, gsttensorrate.c:27-36).

    ``target_interval_ns == 0`` lifts the throttle."""

    target_interval_ns: int = 0


# --------------------------------------------------------------------------
# Pad
# --------------------------------------------------------------------------
class Pad:
    """A connection point on an element.

    Sink pads receive buffers/events (dispatched to the owner element's
    ``chain``/``sink_event``); src pads push to their linked peer.
    """

    def __init__(self, element: "Element", name: str,
                 direction: PadDirection,
                 template_caps: Optional[CapsList] = None):
        self.element = element
        self.name = name
        self.direction = direction
        self.template_caps = template_caps or CapsList.any()
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None  # negotiated, fixed caps
        self.eos = False

    # -- linking -------------------------------------------------------------
    def link(self, sink: "Pad") -> None:
        if self.direction is not PadDirection.SRC:
            raise ValueError(f"{self} is not a src pad")
        if sink.direction is not PadDirection.SINK:
            raise ValueError(f"{sink} is not a sink pad")
        if self.peer is not None or sink.peer is not None:
            raise ValueError(f"pad already linked: {self} / {sink}")
        inter = self.template_caps.intersect(sink.template_caps)
        if inter.is_empty():
            raise ValueError(
                f"cannot link {self} -> {sink}: caps do not intersect "
                f"({self.template_caps} vs {sink.template_caps})"
            )
        self.peer = sink
        sink.peer = self

    def unlink(self) -> None:
        if self.peer is not None:
            self.peer.peer = None
            self.peer = None

    # -- dataflow ------------------------------------------------------------
    def push(self, buf: TensorBuffer) -> FlowReturn:
        """Push a buffer downstream (src pads only)."""
        if self.peer is None:
            return FlowReturn.OK  # unlinked src: drop (gst would error; we
            # drop to allow partial pipelines in tests)
        return self.peer.element._chain_entry(self.peer, buf)

    def push_list(self, bufs: List[TensorBuffer]) -> FlowReturn:
        """Push a backlog of buffers downstream in one hand-off.

        Peers that opt in (``Element.HANDLES_LIST``) receive the whole
        list through one ``chain_list`` call — the batch-drain fast path
        (one lock/wake/entry per backlog instead of per frame). Everyone
        else gets the exact per-buffer push sequence, so opting out is
        always behavior-preserving."""
        if self.peer is None:
            return FlowReturn.OK
        el = self.peer.element
        if getattr(el, "HANDLES_LIST", False) and len(bufs) > 1:
            return el._chain_list_entry(self.peer, bufs)
        ret = FlowReturn.OK
        for b in bufs:
            ret = el._chain_entry(self.peer, b)
            if ret is FlowReturn.EOS:
                return ret
        return ret

    def push_event(self, event: Event) -> None:
        if isinstance(event, CapsEvent):
            self.caps = event.caps
        if self.peer is not None:
            self.peer.element._event_entry(self.peer, event)

    def push_upstream_event(self, event: Event) -> None:
        """Send an event upstream (sink pads only): it arrives on the
        peer src pad and dispatches to that element's ``src_event``."""
        if self.direction is not PadDirection.SINK:
            raise ValueError(f"{self}: upstream events leave via sink pads")
        if self.peer is not None:
            self.peer.element._upstream_event_entry(self.peer, event)

    def set_caps(self, caps: Caps) -> None:
        """Fix this src pad's caps and announce downstream."""
        if not caps.is_fixed():
            caps = caps.fixate()
        self.push_event(CapsEvent(caps))

    def __repr__(self):
        return f"Pad({self.element.name}.{self.name}:{self.direction.value})"


def peer_device_capable(pad: "Pad") -> bool:
    """True when the element behind ``pad``'s peer forwards device-resident
    buffers without a host materialization at entry — emission sites
    (fused regions, device filters) use this to decide whether wrapping
    their output as a DeviceBuffer buys anything."""
    peer = pad.peer
    if peer is None:
        return False
    return bool(getattr(peer.element, "DEVICE_PASSTHROUGH", False))


# --------------------------------------------------------------------------
# Element
# --------------------------------------------------------------------------
class Element:
    """Base class for all stream elements.

    Subclasses declare::

        ELEMENT_NAME = "tensor_something"   # registry name
        PROPERTIES = {"prop": default, ...}

    and override :meth:`chain` (per-buffer work), :meth:`sink_event`
    (negotiation via CapsEvent), and optionally :meth:`start`/:meth:`stop`
    (state changes). Every element gets reference-style ``latency`` /
    ``throughput`` read-outs via :attr:`stats` for free (tensor_filter.c
    exposes these as properties; here they are uniform across elements,
    which is what GstShark's proctime tracer adds externally).
    """

    ELEMENT_NAME = "element"
    #: ``error_policy`` None = inherit ``Pipeline(error_policy=...)``,
    #: else ``halt`` — see pipeline/supervise.py for the policy set and
    #: ``retry_max``/``retry_backoff_ms`` semantics
    PROPERTIES: Dict[str, Any] = {"silent": True, "name": None,
                                  "error_policy": None, "retry_max": 3,
                                  "retry_backoff_ms": 5.0}

    _instance_counter: Dict[str, int] = {}
    _instance_counter_lock = threading.Lock()

    @classmethod
    def _next_auto_name(cls) -> str:
        with Element._instance_counter_lock:
            n = Element._instance_counter.get(cls.ELEMENT_NAME, 0)
            Element._instance_counter[cls.ELEMENT_NAME] = n + 1
        return f"{cls.ELEMENT_NAME}{n}"

    def __init__(self, name: Optional[str] = None, **props):
        cls_props: Dict[str, Any] = {}
        for klass in reversed(type(self).__mro__):
            cls_props.update(getattr(klass, "PROPERTIES", {}))
        self._props = dict(cls_props)
        self.name = name or self._next_auto_name()
        self.log = get_logger(self.name)
        self.sinkpads: List[Pad] = []
        self.srcpads: List[Pad] = []
        self.stats = InvokeStats()
        self.pipeline = None  # set by Pipeline.add
        self._obs_hist = None  # per-element chain histogram, lazy
        self._started = False
        self._lock = threading.RLock()
        for k, v in props.items():
            self.set_property(k, v)

    # -- properties ----------------------------------------------------------
    def set_property(self, key: str, value: Any) -> None:
        key = key.replace("-", "_")
        if key not in self._props:
            raise KeyError(
                f"{self.ELEMENT_NAME} has no property {key!r} "
                f"(has: {sorted(self._props)})"
            )
        self._props[key] = self._coerce_property(key, value)
        self.property_changed(key)
        region = getattr(self, "_fused_region", None)
        if region is not None:
            # a live property edit may change the member's computation (or
            # its fusibility — e.g. throttle>0); re-plan on the next frame
            region.invalidate()

    def get_property(self, key: str) -> Any:
        key = key.replace("-", "_")
        if key in ("latency", "throughput"):
            stats = self._metrics_stats()
            return stats.latency_us if key == "latency" else \
                stats.throughput_milli
        return self._props[key]

    def _metrics_stats(self):
        """The InvokeStats behind the ``latency``/``throughput``
        properties. Default: this element's chain window; a fused member
        that doesn't run its own chain reads the region's single-dispatch
        stat (documented: when fused, element latency == region dispatch
        latency). Async elements override to report the meaningful
        window (e.g. tensor_lm_serve's submit→completion per request)."""
        stats = self.stats
        region = getattr(self, "_fused_region", None)
        if region is not None and stats.total_invokes == 0:
            stats = region.stats
        return stats

    def _coerce_property(self, key: str, value: Any) -> Any:
        """Coerce string property values (from parse_launch) to the default's
        type."""
        default = self._props.get(key)
        if isinstance(value, str):
            if isinstance(default, bool):
                return value.strip().lower() in ("1", "true", "yes", "on")
            if isinstance(default, int) and not isinstance(default, bool):
                return int(value)
            if isinstance(default, float):
                return float(value)
        return value

    def property_changed(self, key: str) -> None:
        """Hook: subclass reacts to a property update."""

    # -- pad management ------------------------------------------------------
    def add_sink_pad(self, name: str = "sink", caps: Optional[CapsList] = None
                     ) -> Pad:
        pad = Pad(self, name, PadDirection.SINK, caps)
        self.sinkpads.append(pad)
        return pad

    def add_src_pad(self, name: str = "src", caps: Optional[CapsList] = None
                    ) -> Pad:
        pad = Pad(self, name, PadDirection.SRC, caps)
        self.srcpads.append(pad)
        return pad

    def request_sink_pad(self) -> Pad:
        """For N-input elements (mux/merge/join): allocate a new sink pad.
        Default: error — override in request-pad elements."""
        raise NotImplementedError(f"{self.ELEMENT_NAME} has fixed pads")

    def request_src_pad(self) -> Pad:
        """For N-output elements (tee/split/demux): allocate a new src
        pad. Default: error — override in request-pad elements."""
        raise NotImplementedError(f"{self.ELEMENT_NAME} has fixed src pads")

    @property
    def sinkpad(self) -> Pad:
        return self.sinkpads[0]

    @property
    def srcpad(self) -> Pad:
        return self.srcpads[0]

    def link(self, downstream: "Element") -> "Element":
        """Link this element's first free src pad to downstream's first free
        sink pad (gst_element_link). Returns downstream for chaining."""
        src = next((p for p in self.srcpads if p.peer is None), None)
        if src is None:
            raise ValueError(f"{self.name}: no free src pad")
        sink = next((p for p in downstream.sinkpads if p.peer is None), None)
        if sink is None:
            sink = downstream.request_sink_pad()
        src.link(sink)
        return downstream

    # -- dataflow entry (with uniform instrumentation) -----------------------
    #: Elements that merely hold or hand off buffers (queue, sinks) set this
    #: True to keep a pending ``TensorBuffer.finalize`` lazy. Everything else
    #: materializes a finalize-pending buffer on entry, so elements always
    #: see the same payload they would in an unfused pipeline.
    HANDLES_DEFERRED = False

    #: Elements that accept a whole buffer backlog per entry (aggregator,
    #: fused regions) set this True; a batch-draining queue then hands its
    #: backlog through ONE ``chain_list`` call instead of a per-buffer
    #: push sequence. Ordering is identical — the list preserves queue
    #: order and ``chain_list`` consumes it in order.
    HANDLES_LIST = False

    #: Elements that route/hold/compute without reading tensor bytes on the
    #: host (queue, tee, mux, demux, split, aggregator, device-capable
    #: filters/transforms, sinks with their own sanctioned fetch point) set
    #: this True: a :class:`~nnstreamer_tpu.tensors.buffer.DeviceBuffer`
    #: then crosses their pads without materializing. Everything else gets
    #: the buffer host-materialized at pad entry — one sanctioned
    #: ``to_host()`` whose cost lands in that element's chain stats.
    DEVICE_PASSTHROUGH = False

    #: Elements whose per-buffer output is a pure function of the input
    #: buffer and their (fixed) properties — no per-frame mutable state —
    #: set this True: the ingest lane planner (``pipeline/lanes.py``) may
    #: replicate them across parallel worker lanes, process frames out of
    #: order, and reassemble by sequence number without changing a byte.
    #: On a SourceElement the flag means each ``create()`` output is
    #: self-contained (pts stamped at the source, no downstream feedback),
    #: so stamped sequence numbers fully determine stream order. The
    #: NNS109 lint rule statically audits declarations against per-frame
    #: ``chain`` state mutations.
    REORDER_SAFE = False

    #: This element's jitted program may consume (donate) an incoming
    #: single-consumer payload — only the fused region sets this. Every
    #: OTHER element strips the upload point's exclusivity marker at pad
    #: entry: once a payload has crossed a non-consuming element its
    #: ownership chain is unprovable (meta is copied onto derived
    #: buffers), so donation must not trust a stale marker.
    DONATION_CONSUMER = False

    def reorder_safe(self) -> bool:
        """Instance-level lane-replicability check; defaults to the class
        flag. Elements that are only conditionally stateless
        (tensor_converter: per-buffer regimes yes, cross-frame adapters
        no) override this with a property-aware answer."""
        return bool(self.REORDER_SAFE)

    def _obs_labels(self) -> Dict[str, str]:
        """Stable metric labels: ``{pipeline=..., element=...}`` (the
        ``nns_<element>_<metric>`` naming scheme's label half)."""
        return {"pipeline": getattr(self.pipeline, "name", "") or "",
                "element": self.name}

    def _obs_chain_hist(self):
        """The per-element chain-latency histogram (lazy: labels include
        the owning pipeline's name, known only after Pipeline.add)."""
        h = self._obs_hist
        if h is None:
            h = self._obs_hist = get_registry().histogram(
                "nns_element_chain_seconds",
                "Per-buffer chain duration (invoke + downstream push)",
                **self._obs_labels())
        return h

    def obs_snapshot(self) -> Dict[str, Any]:
        """Element-specific extras for ``Pipeline.metrics_snapshot()``
        (subclasses add drops, depth, e2e percentiles, ...)."""
        h = self._obs_hist
        if h is None or h.count == 0:
            return {}
        p50, p99 = h.percentile(50), h.percentile(99)
        return {"chain_p50_ms": round(p50 * 1e3, 3),
                "chain_p99_ms": round(p99 * 1e3, 3)}

    def _chain_entry(self, pad: Pad, buf: TensorBuffer) -> FlowReturn:
        if pad.eos:
            return FlowReturn.EOS
        t0 = _time.monotonic()
        try:
            try:
                if not self.DONATION_CONSUMER and \
                        H2D_EXCLUSIVE_META in buf.meta:
                    buf.meta.pop(H2D_EXCLUSIVE_META, None)
                if isinstance(buf, DeviceBuffer):
                    # a resident buffer stays resident across elements that
                    # declared passthrough (finalize-free payloads) or that
                    # keep deferred work lazy (they own their fetch point,
                    # so device payloads cross them untouched, exactly as
                    # before residency); otherwise this entry is the
                    # sanctioned (cached) materialization point
                    resident = self.HANDLES_DEFERRED or (
                        self.DEVICE_PASSTHROUGH and buf.finalize is None)
                    record_residency_entry(resident)
                    if not resident:
                        buf = buf.to_host()
                elif buf.finalize is not None and not self.HANDLES_DEFERRED:
                    # blocking D2H + host finalize — inside the timed span
                    # so the element paying the sync is the one whose
                    # stats show it
                    buf = buf.to_host()
                ret = self.chain(pad, buf)
            except FlowError:
                raise
            except Exception as e:
                ret = self._recover_chain(pad, buf, e)
        finally:
            now = _time.monotonic()
            self.stats.record(now - t0, now)
            self._obs_chain_hist().observe(now - t0)
        return FlowReturn.OK if ret is None else ret

    def _chain_list_entry(self, pad: Pad,
                          bufs: List[TensorBuffer]) -> FlowReturn:
        """Batch twin of :meth:`_chain_entry` (``Pad.push_list`` → here).
        Same deferred-finalize contract per buffer; stats attribute the
        batch duration evenly across its buffers so invoke counts and
        throughput read the same as the per-buffer path."""
        if pad.eos:
            return FlowReturn.EOS
        t0 = _time.monotonic()
        try:
            try:
                entered = []
                for b in bufs:
                    if not self.DONATION_CONSUMER and \
                            H2D_EXCLUSIVE_META in b.meta:
                        b.meta.pop(H2D_EXCLUSIVE_META, None)
                    if isinstance(b, DeviceBuffer):
                        resident = self.HANDLES_DEFERRED or (
                            self.DEVICE_PASSTHROUGH and b.finalize is None)
                        record_residency_entry(resident)
                        if not resident:
                            b = b.to_host()
                    elif b.finalize is not None and not self.HANDLES_DEFERRED:
                        b = b.to_host()
                    entered.append(b)
                bufs = entered
                ret = self.chain_list(pad, bufs)
            except FlowError:
                raise
            except Exception as e:
                ret = self._recover_chain_list(pad, bufs, e)
        finally:
            now = _time.monotonic()
            per = (now - t0) / max(len(bufs), 1)
            hist = self._obs_chain_hist()
            for _ in range(max(len(bufs), 1)):
                self.stats.record(per, now)
                hist.observe(per)
        return FlowReturn.OK if ret is None else ret

    def _halt_policy(self) -> bool:
        """True when this element's effective error policy is ``halt``
        (the default). Decided from the two property reads alone so the
        common no-supervision case never imports the recovery module."""
        pol = self._props.get("error_policy") or \
            getattr(self.pipeline, "error_policy", None)
        return not pol or str(pol).replace("_", "-") == "halt"

    def _recover_chain(self, pad: Pad, buf: TensorBuffer,
                       exc: BaseException) -> FlowReturn:
        """A ``chain`` call raised a non-FlowError: apply the element's
        error policy (``pipeline/supervise.py``). ``halt`` reproduces
        the historical wrap-and-raise exactly."""
        if self._halt_policy():
            raise FlowError(f"{self.name}: {exc}") from exc
        from nnstreamer_tpu.pipeline import supervise

        return supervise.recover_chain(self, pad, buf, exc)

    def _recover_chain_list(self, pad: Pad, bufs: List[TensorBuffer],
                            exc: BaseException) -> FlowReturn:
        if self._halt_policy():
            raise FlowError(f"{self.name}: {exc}") from exc
        from nnstreamer_tpu.pipeline import supervise

        return supervise.recover_chain_list(self, pad, bufs, exc)

    def _event_entry(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.caps = event.caps
        if isinstance(event, EosEvent):
            pad.eos = True
        self.sink_event(pad, event)

    def _upstream_event_entry(self, pad: Pad, event: Event) -> None:
        self.src_event(pad, event)

    # -- subclass hooks ------------------------------------------------------
    def chain(self, pad: Pad, buf: TensorBuffer) -> Optional[FlowReturn]:
        """Process one input buffer. Default: passthrough to first src pad."""
        if self.srcpads:
            return self.srcpad.push(buf)
        return FlowReturn.OK

    def chain_list(self, pad: Pad, bufs: List[TensorBuffer]
                   ) -> Optional[FlowReturn]:
        """Process a queue-drained backlog in order. Default: loop
        :meth:`chain`; HANDLES_LIST elements may override to hoist
        per-buffer overhead (e.g. one lock acquisition per backlog)."""
        ret = None
        for i, b in enumerate(bufs):
            try:
                ret = self.chain(pad, b)
            except Exception as e:
                # buffers before index i were fully chained (and pushed
                # downstream) — record the progress so a non-halt error
                # policy replays only the unconsumed suffix instead of
                # re-pushing delivered frames (duplication)
                if getattr(e, "_nns_list_done", None) is None:
                    try:
                        e._nns_list_done = i
                    except Exception:  # nns-lint: disable=NNS104 -- exceptions with __slots__ just lose the replay hint; the original error re-raises below
                        pass
                raise
            if ret is FlowReturn.EOS:
                break
        return ret

    def src_event(self, pad: Pad, event: Event) -> None:
        """Handle an upstream-flowing event arriving on a src pad.
        Default: forward further upstream through every sink pad."""
        for sp in self.sinkpads:
            sp.push_upstream_event(event)

    def _qos_throttled(self, min_interval_s: float = 0.0) -> bool:
        """Shared invoke drop check (tensor_filter.c:426): True when this
        invoke must be skipped to honor the larger of the element's own
        minimum interval and the downstream QoS interval adopted in
        ``src_event`` (``_qos_interval_s``). Updates the invoke clock when
        the invoke is allowed."""
        interval = max(min_interval_s,
                       getattr(self, "_qos_interval_s", 0.0))
        if interval <= 0:
            return False
        import time

        now = time.monotonic()
        if now - getattr(self, "_last_invoke_t", 0.0) < interval:
            return True
        self._last_invoke_t = now
        return False

    def sink_event(self, pad: Pad, event: Event) -> None:
        """Handle a downstream-flowing event. Default: CAPS → negotiate via
        :meth:`transform_caps`; EOS/custom → forward when all sink pads agree.
        """
        if isinstance(event, CapsEvent):
            out = self.transform_caps(pad, event.caps)
            if out is not None and self.srcpads:
                for sp in self.srcpads:
                    sp.set_caps(out)
        elif isinstance(event, EosEvent):
            if all(p.eos for p in self.sinkpads):
                self.handle_eos()
                for sp in self.srcpads:
                    sp.push_event(event)
        else:
            for sp in self.srcpads:
                sp.push_event(event)

    def transform_caps(self, pad: Pad, caps: Caps) -> Optional[Caps]:
        """Map fixed input caps to output caps. Default: identity."""
        return caps

    def handle_eos(self) -> None:
        """Hook: flush buffered state at end-of-stream."""

    # -- state ---------------------------------------------------------------
    def start(self) -> None:
        """Transition to streaming state (allocate resources, open models)."""
        self._started = True

    def stop(self) -> None:
        self._started = False

    def post_error(self, exc: Exception) -> None:
        if self.pipeline is not None:
            self.pipeline.post_error(self, exc)
        else:
            raise exc

    def post_warning(self, text: str) -> None:
        """Post a non-fatal condition to the pipeline bus (logged and
        delivered as a ``warning`` message; ``wait()`` keeps running)."""
        if self.pipeline is not None:
            self.pipeline.post_warning(self, text)
        else:
            self.log.warning("%s: %s", self.name, text)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"
