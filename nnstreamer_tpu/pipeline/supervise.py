"""Supervision layer — per-element error policies and the pipeline watchdog.

Before this module, any exception in any element's ``chain`` became a
``FlowError`` on the bus and the whole pipeline died (or worse, an EOS
drain hung forever waiting for a frame that would never arrive). The
reference's value proposition is that inference is "just another robust
stream filter"; robustness here is the per-element ``error-policy``
property, enforced at the uniform ``_chain_entry`` boundary
(``pipeline/element.py``):

- ``halt``       — (default) current behavior: wrap, raise, bus error.
- ``skip-frame`` — drop the failing frame, count it
  (``nns_fault_skipped_frames_total``), keep streaming. Loss equals the
  failure count; everything else is byte-identical.
- ``retry``      — re-run the element's ``chain`` up to ``retry-max``
  times with bounded exponential backoff + deterministic jitter
  (``retry-backoff-ms`` base, 1 s cap). The burnt wall time is reported
  to the SLO scheduler (:meth:`SloScheduler.note_retry`) so admission
  tightens during a brownout instead of over-admitting against a
  service-rate estimate that no longer holds. Retries exhausted →
  ``halt``.
- ``degrade``    — ``tensor_filter`` only: reload the backend and retry
  once; still failing → reopen with ``accelerator=cpu`` (the device is
  presumed sick) and retry once more; still failing → ``halt``. Other
  elements fall back to ``retry`` semantics.

  A *memory-pressure* failure (injected ``kind=oom`` or a real
  ``RESOURCE_EXHAUSTED``) takes the memory ladder instead — in order:
  **evict** cold residency units to host staging
  (``tensors/memory.py``) → **pool**: drain the dispatch window and
  release every pool arena's free slabs → **shed**: raise the SLO
  scheduler's memory-backlog term so new frames shed at admission →
  **cpu**: reopen ``accelerator=cpu``, today's last rung. Each rung
  retries the frame; zero frame loss when any rung recovers.

The **watchdog** (:class:`PipelineWatchdog`) is the liveness half: a
thread that samples a pipeline-wide progress vector (chain invokes,
lane deliveries, sink completions) and, when in-flight work exists but
no progress lands within ``watchdog_s``, fails the pipeline — a bus
error naming the stalled elements, sources parked — instead of hanging
a fence or an EOS drain forever. Enabled per pipeline
(``Pipeline(watchdog_s=...)``, ``nns-launch --watchdog-s``) or via
``NNSTPU_WATCHDOG_S``; default off, zero threads, byte-identical.

Every recovery emits ``nns_fault_*`` metrics and a frame-ledger mark
(``fault_retry`` / ``fault_skip`` / ``fault_degrade`` /
``watchdog_trip``) so PR 7's timeline shows which frames died and why.
See docs/robustness.md.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline.element import FlowError, FlowReturn

log = get_logger("supervise")

POLICIES: Tuple[str, ...] = ("halt", "skip-frame", "retry", "degrade")

#: backoff ceiling — a retry ladder must never park a streaming thread
#: for longer than this per attempt
_BACKOFF_CAP_S = 1.0


def effective_policy(el) -> str:
    """The element's error policy: its own property first, then the
    pipeline-level default (``Pipeline(error_policy=...)``), then
    ``halt``. Read only on the error path — the hot path never pays."""
    pol = el._props.get("error_policy")
    if not pol:
        pol = getattr(el.pipeline, "error_policy", None) or "halt"
    pol = str(pol).replace("_", "-")
    if pol not in POLICIES:
        raise FlowError(
            f"{el.name}: unknown error-policy {pol!r} "
            f"(policies: {', '.join(POLICIES)})")
    return pol


def _metrics(el) -> Dict[str, Any]:
    """Per-element recovery counters, cached on the element (created on
    first failure — a healthy pipeline allocates nothing)."""
    m = getattr(el, "_supervise_m", None)
    if m is None:
        reg = get_registry()
        labels = el._obs_labels()
        m = el._supervise_m = {
            "retries": reg.counter(
                "nns_fault_retries_total",
                "Chain re-invocations under error-policy=retry/degrade",
                **labels),
            "recovered": reg.counter(
                "nns_fault_recovered_total",
                "Failures recovered without frame loss (retry/degrade "
                "succeeded)", **labels),
            "skipped": reg.counter(
                "nns_fault_skipped_frames_total",
                "Frames dropped under error-policy=skip-frame", **labels),
            "degraded": reg.counter(
                "nns_fault_degraded_total",
                "Degrade-ladder rungs taken (backend reload / CPU "
                "fallback)", **labels),
        }
    return m


def _mark(kind: str, buf, **args) -> None:
    tl = _timeline.ACTIVE
    if tl is not None:
        seq = buf.meta.get(_timeline.TRACE_SEQ_META) \
            if buf is not None else None
        tl.mark(kind, seq, track="faults", **args)


def _note_scheduler_retry(el, busy_s: float) -> None:
    """Feed the wall time burnt on failed attempts + backoff into the
    SLO scheduler's service-rate estimate: during a brownout each served
    frame effectively costs its retries too, and admission computed from
    the healthy-path estimate would over-admit exactly when capacity is
    lowest."""
    sched = getattr(el.pipeline, "_slo_scheduler", None)
    if sched is not None and busy_s > 0:
        sched.note_retry(busy_s)


def _backoff_sleep(el, attempt: int, base_ms: float) -> float:
    """Bounded exponential backoff with deterministic jitter: the delay
    for (element, attempt) is a pure function, so a seeded fault spec
    reproduces the same recovery timeline run over run."""
    base_s = max(0.0, float(base_ms)) / 1e3
    delay = min(base_s * (2 ** (attempt - 1)), _BACKOFF_CAP_S)
    # string seed: sha512-based, stable across processes (tuple seeds
    # hash through PYTHONHASHSEED and would vary run to run)
    jitter = 0.5 + 0.5 * random.Random(f"{el.name}:{attempt}").random()
    delay *= jitter
    if delay > 0:
        time.sleep(delay)
    return delay


# --------------------------------------------------------------------------
# chain-error recovery (called from Element._chain_entry's except path)
# --------------------------------------------------------------------------
def recover_chain(el, pad, buf, exc: BaseException) -> FlowReturn:
    """Apply the element's non-halt error policy to a failed ``chain``
    invocation. Returns the recovered FlowReturn or raises ``FlowError``
    when the policy is exhausted (halt semantics)."""
    policy = effective_policy(el)
    if policy == "retry":
        return _retry(el, pad, buf, exc)
    if policy == "degrade":
        return _degrade(el, pad, buf, exc)
    if policy == "skip-frame":
        return _skip(el, buf, exc)
    raise _wrap(el, exc)  # halt


def recover_chain_list(el, pad, bufs: List[Any],
                       exc: BaseException) -> FlowReturn:
    """List-entry twin: a failed ``chain_list`` falls back to per-buffer
    ``chain`` calls with the policy applied per frame, so one poisoned
    frame in a drained batch costs (at most) itself, not the batch."""
    policy = effective_policy(el)
    if policy == "halt":
        raise _wrap(el, exc)
    # the default chain_list marks how many leading buffers were fully
    # chained before the failure — those already pushed downstream, so
    # replaying them would DUPLICATE delivered frames. Custom chain_list
    # implementations without the marker keep the replay-all behavior.
    done = int(getattr(exc, "_nns_list_done", 0) or 0)
    if 0 < done <= len(bufs):
        bufs = bufs[done:]
    log.warning("%s: chain_list failed (%s); replaying %d undelivered "
                "buffer(s) individually under error-policy=%s", el.name,
                exc, len(bufs), policy)
    ret: FlowReturn = FlowReturn.OK
    for b in bufs:
        try:
            r = el.chain(pad, b)
        except Exception as e:  # noqa: BLE001 — per-frame policy below
            r = recover_chain(el, pad, b, e)
        if r is FlowReturn.EOS:
            return r
        if r is not None:
            ret = r
    return ret


def _wrap(el, exc: BaseException) -> FlowError:
    return exc if isinstance(exc, FlowError) \
        else FlowError(f"{el.name}: {exc}")


def _skip(el, buf, exc: BaseException) -> FlowReturn:
    m = _metrics(el)
    m["skipped"].inc()
    _mark("fault_skip", buf, element=el.name)
    el.log.warning("%s: dropping frame under error-policy=skip-frame: %s",
                   el.name, exc)
    # an admitted frame that dies here leaves the served population —
    # revoke the stamp so shared-meta consumers never report it as a
    # served-latency sample (same contract as scheduler shedding)
    if buf is not None:
        buf.meta.pop("admitted_t", None)
    return FlowReturn.OK


def _retry(el, pad, buf, exc: BaseException,
           exhausted: str = "halt") -> FlowReturn:
    m = _metrics(el)
    retry_max = max(1, int(el._props.get("retry_max") or 3))
    base_ms = float(el._props.get("retry_backoff_ms") or 5.0)
    t0 = time.monotonic()
    last: BaseException = exc
    for attempt in range(1, retry_max + 1):
        _backoff_sleep(el, attempt, base_ms)
        m["retries"].inc()
        _mark("fault_retry", buf, element=el.name, attempt=attempt)
        try:
            ret = el.chain(pad, buf)
        except Exception as e:  # noqa: BLE001 — bounded ladder, re-raised
            # as FlowError below when attempts run out
            last = e
            continue
        _note_scheduler_retry(el, time.monotonic() - t0)
        m["recovered"].inc()
        el.log.warning("%s: recovered on retry %d/%d (first failure: %s)",
                       el.name, attempt, retry_max, exc)
        return FlowReturn.OK if ret is None else ret
    _note_scheduler_retry(el, time.monotonic() - t0)
    if exhausted == "skip":
        return _skip(el, buf, last)
    raise FlowError(
        f"{el.name}: error-policy=retry exhausted after {retry_max} "
        f"attempt(s): {last}") from last


def _is_memory_pressure(exc: BaseException) -> bool:
    """Discriminate an OOM-class failure from an ordinary backend fault:
    an injected ``kind=oom`` fault, or a runtime error whose text carries
    the XLA/driver exhaustion signatures."""
    from nnstreamer_tpu.pipeline.faults import InjectedFault

    if isinstance(exc, InjectedFault):
        return exc.kind == "oom"
    text = str(exc).lower()
    return ("resource_exhausted" in text or "out of memory" in text
            or "ran out of memory" in text)


def _degrade(el, pad, buf, exc: BaseException) -> FlowReturn:
    """The tensor_filter degrade ladder: (1) reload the backend in place
    and retry — a wedged session/compilation cache is the common
    transient; (2) reopen with ``accelerator=cpu`` and retry — the
    accelerator is presumed sick, serve degraded rather than die;
    (3) halt. Elements without a backend get ``retry`` semantics.
    OOM-class failures take :func:`_pressure_ladder` instead — the
    accelerator isn't sick, it's FULL, and a reload would re-lose the
    same allocation race."""
    if _is_memory_pressure(exc):
        return _pressure_ladder(el, pad, buf, exc)
    if not hasattr(el, "_open_fw"):
        log.warning("%s: error-policy=degrade on a non-filter element — "
                    "applying retry semantics", el.name)
        return _retry(el, pad, buf, exc)
    m = _metrics(el)
    last = exc
    for stage in ("reload", "cpu"):
        m["degraded"].inc()
        _mark("fault_degrade", buf, element=el.name, stage=stage)
        try:
            _reopen_backend(el, force_cpu=(stage == "cpu"))
        except Exception as e:  # noqa: BLE001 — a failed reopen is just
            # a failed rung; the ladder continues (cpu) or halts below
            el.log.warning("%s: degrade stage %r reopen failed: %s",
                           el.name, stage, e)
            last = e
            continue
        m["retries"].inc()
        try:
            ret = el.chain(pad, buf)
        except Exception as e:  # noqa: BLE001 — next rung or halt below
            last = e
            continue
        m["recovered"].inc()
        el.log.warning(
            "%s: degraded (%s) after backend failure: %s", el.name,
            "reloaded backend" if stage == "reload"
            else "CPU fallback", exc)
        return FlowReturn.OK if ret is None else ret
    raise FlowError(
        f"{el.name}: error-policy=degrade exhausted "
        f"(reload + CPU fallback both failed): {last}") from last


def _pressure_ladder(el, pad, buf, exc: BaseException) -> FlowReturn:
    """The memory-pressure rungs, in escalation order (see
    ``tensors/memory.py`` PRESSURE_RUNGS and docs/robustness.md):

    1. ``evict`` — drop every resident weight unit to host staging; the
       one this frame needs prefetches back in on the retry.
    2. ``pool``  — drain the element's dispatch window (outstanding
       batches release their staging stashes) and free every pool
       arena's free-listed slabs.
    3. ``shed``  — tell the SLO scheduler to shed at admission for a
       while (memory-backlog term) so retried work isn't racing fresh
       arrivals for the same headroom; reclaim again.
    4. ``cpu``   — reopen with ``accelerator=cpu`` (filters only): host
       RAM is the spill of last resort, exactly today's final rung.

    Every rung counts ``nns_fault_degraded_total`` and
    ``nns_mem_pressure_events_total{rung=...}`` and marks the ledger, so
    a recovery is attributable to the rung that made room."""
    m = _metrics(el)
    last = exc
    rungs = ["evict", "pool", "shed"]
    if hasattr(el, "_open_fw"):
        rungs.append("cpu")
    for rung in rungs:
        m["degraded"].inc()
        _mark("fault_degrade", buf, element=el.name, stage=rung)
        _count_pressure_rung(rung)
        try:
            _apply_pressure_rung(el, rung)
        except Exception as e:  # noqa: BLE001 — a failed rung is just a
            # failed rung; escalation continues and halt is below
            el.log.warning("%s: pressure rung %r failed: %s",
                           el.name, rung, e)
            last = e
            continue
        m["retries"].inc()
        try:
            ret = el.chain(pad, buf)
        except Exception as e:  # noqa: BLE001 — next rung or halt below
            last = e
            continue
        m["recovered"].inc()
        el.log.warning("%s: recovered from memory pressure at rung %r "
                       "(first failure: %s)", el.name, rung, exc)
        return FlowReturn.OK if ret is None else ret
    raise FlowError(
        f"{el.name}: memory-pressure ladder exhausted "
        f"({' → '.join(rungs)} all failed): {last}") from last


def _count_pressure_rung(rung: str) -> None:
    import sys

    mem = sys.modules.get("nnstreamer_tpu.tensors.memory")
    if mem is not None and mem.ACTIVE is not None:
        mem.ACTIVE.pressure_events += 1
        mem.ACTIVE.count_pressure(rung)


def _apply_pressure_rung(el, rung: str) -> None:
    """The reclamation action for one rung (no retry here — the caller
    owns the retry loop)."""
    import sys

    mem = sys.modules.get("nnstreamer_tpu.tensors.memory")
    acct = mem.ACTIVE if mem is not None else None
    if rung == "evict":
        if acct is not None:
            acct.residency.evict_all()
        return
    if rung == "pool":
        from nnstreamer_tpu.tensors.pool import release_all_pools

        window = getattr(el, "_window", None)
        if window is not None:
            window.drain(on_error="log")
        release_all_pools()
        return
    if rung == "shed":
        sched = getattr(el.pipeline, "_slo_scheduler", None)
        if sched is not None:
            sched.note_memory_pressure()
        # shedding only relieves FUTURE admissions; this frame still
        # needs room now, so run the reclamation rungs again too
        if acct is not None:
            acct.residency.evict_all()
        from nnstreamer_tpu.tensors.pool import release_all_pools

        release_all_pools()
        return
    if rung == "cpu":
        _reopen_backend(el, force_cpu=True)


def _reopen_backend(el, force_cpu: bool) -> None:
    """Close and reopen a tensor_filter's backend, optionally pinned to
    the CPU. Outstanding dispatches read the old backend's params, so
    the window is fenced (errors logged, not raised — the batch that
    poisoned it is the reason we're here) before the close."""
    window = getattr(el, "_window", None)
    if window is not None:
        window.drain(on_error="log")
    # the drained window just released its staging stashes — return the
    # arenas' free slabs too: a reopen (especially force_cpu) means the
    # old working set's peak-rate slabs are dead weight
    from nnstreamer_tpu.tensors.pool import release_all_pools

    release_all_pools()
    if el.fw is not None:
        try:
            el.fw.close()
        except Exception as e:  # noqa: BLE001 — a dying backend failing
            # to close cleanly must not block its own replacement
            el.log.warning("%s: backend close during degrade failed: %s",
                           el.name, e)
        el.fw = None
    if force_cpu:
        el._props["accelerator"] = "cpu"
    el._open_fw()
    region = getattr(el, "_fused_region", None)
    if region is not None:
        region.invalidate()


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------
class PipelineWatchdog:
    """Liveness monitor: fails a stalled pipeline instead of letting a
    wedged fence or EOS drain hang forever.

    Samples a progress vector — total chain invokes across elements,
    lane-executor deliveries, queue depths, dispatch-window occupancy,
    live source threads. A trip requires BOTH no progress for
    ``deadline_s`` AND evidence of in-flight work (depth, window
    occupancy, or a live source): a pipeline that drained cleanly and
    sits idle after EOS never trips. On trip it posts a bus error
    naming the suspect elements, parks the sources, and bumps
    ``nns_fault_watchdog_trips_total`` — ``stop()`` then tears down as
    for any other bus error."""

    def __init__(self, pipeline, deadline_s: float,
                 poll_s: Optional[float] = None):
        self.pipeline = pipeline
        self.deadline_s = float(deadline_s)
        self.poll_s = poll_s if poll_s is not None \
            else max(0.05, min(self.deadline_s / 4.0, 1.0))
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.trips = 0
        self._m_trips = get_registry().counter(
            "nns_fault_watchdog_trips_total",
            "Watchdog detections of a stalled pipeline (no sink/chain "
            "progress within the deadline while work was in flight)",
            pipeline=pipeline.name)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"{self.pipeline.name}-watchdog",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            if t.is_alive():
                log.warning("%s: watchdog thread leaked past stop()",
                            self.pipeline.name)
            self._thread = None

    # -- sampling ------------------------------------------------------------
    def _progress_vector(self) -> Tuple[int, ...]:
        """Monotone counters that advance whenever any frame moves."""
        pipe = self.pipeline
        total = 0
        for el in pipe.elements:
            total += el.stats.total_invokes
        delivered = 0
        for ex in pipe._lane_execs or ():
            delivered += ex._delivered
        return (total, delivered)

    def _inflight_evidence(self) -> List[str]:
        """Names of elements that hold undelivered work — the idle-vs-
        stalled discriminator and the trip message's suspect list."""
        pipe = self.pipeline
        suspects: List[str] = []
        for el in pipe.elements:
            depth = getattr(el, "_depth", None)
            if depth is not None and depth() > 0:
                suspects.append(f"{el.name} (queue depth {depth()})")
            window = getattr(el, "_window", None)
            if window is not None and len(window) > 0:
                suspects.append(
                    f"{el.name} (dispatch window {len(window)} in flight)")
        for ex in pipe._lane_execs or ():
            backlog = ex._seq - ex._delivered
            if backlog > 0:
                suspects.append(f"{ex.name} (reorder backlog {backlog})")
        if any(t.is_alive() for t in pipe._threads):
            suspects.append("live source thread")
        return suspects

    def _run(self) -> None:
        from nnstreamer_tpu.pipeline.pipeline import State

        last = self._progress_vector()
        last_t = time.monotonic()
        while not self._stop_evt.wait(self.poll_s):
            if self.pipeline.state is not State.PLAYING:
                last_t = time.monotonic()
                continue
            cur = self._progress_vector()
            now = time.monotonic()
            if cur != last:
                last, last_t = cur, now
                continue
            if now - last_t < self.deadline_s:
                continue
            suspects = self._inflight_evidence()
            if not suspects:
                # quiescent, not stalled (post-EOS idle): keep watching
                last_t = now
                continue
            self._trip(now - last_t, suspects)
            return  # one trip per run: teardown is already in motion

    def _trip(self, stalled_s: float, suspects: List[str]) -> None:
        self.trips += 1
        self._m_trips.inc()
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("watchdog_trip", None, track="faults",
                    stalled_s=round(stalled_s, 3))
        err = FlowError(
            f"watchdog: no pipeline progress for {stalled_s:.1f}s "
            f"(deadline {self.deadline_s:.1f}s) with work in flight — "
            f"{'; '.join(suspects)}")
        log.error("%s: %s", self.pipeline.name, err)
        # park the sources so no new frames pile onto the stall, then
        # fail the pipeline: wait()/run() returns the error and stop()
        # fences what it can on the way down
        from nnstreamer_tpu.pipeline.pipeline import SourceElement

        for el in self.pipeline.elements:
            if isinstance(el, SourceElement):
                el._stop_evt.set()
        # a stalled pipeline's staging arenas hold its peak working set;
        # nothing will recycle them while the stall holds, so free the
        # pools' idle slabs as part of failing it
        from nnstreamer_tpu.tensors.pool import release_all_pools

        release_all_pools()
        self.pipeline.post_error(None, err)
