"""Pipeline graph dumps in Graphviz dot format.

The reference inherits GStreamer's ``GST_DEBUG_DUMP_DOT_DIR``: set the
env var, and every pipeline state change writes a ``.dot`` of the runtime
graph — the standard way to debug caps negotiation and topology
(referenced throughout /root/reference/Documentation, e.g.
debugging how-tos). Equivalent here:

- ``pipeline_to_dot(pipe)`` — dot text for the CURRENT runtime graph:
  elements, pad links, negotiated caps on edges, and fused regions drawn
  as clusters around their member elements (so the TPU-specific region
  compilation is visible, not hidden).
- ``NNSTPU_DUMP_DOT_DIR=<dir>`` — every ``Pipeline.start()`` writes
  ``<serial>-<name>.playing.dot`` there (serial keeps repeated runs
  distinct, mirroring the reference's timestamped dumps).
- ``nns-launch --dot FILE`` writes the started graph and keeps running.

Render with ``dot -Tpng out.dot``.
"""

from __future__ import annotations

import itertools
import os
from typing import List

_serial = itertools.count()


def _esc(s: str) -> str:
    return str(s).replace('"', '\\"')


def _caps_label(pad) -> str:
    caps = getattr(pad, "caps", None)
    return _esc(str(caps)) if caps is not None else ""


def pipeline_to_dot(pipe) -> str:
    """Dot text for a pipeline's current element/link graph."""
    from nnstreamer_tpu.pipeline.fuse import FusedRegion

    lines: List[str] = [
        "digraph pipeline {",
        "  rankdir=LR;",
        "  node [shape=box, fontsize=10, fontname=monospace];",
        "  edge [fontsize=8, fontname=monospace];",
        f'  label="{_esc(pipe.name)} ({pipe.state.value})";',
    ]
    regions = [r for r in (pipe._regions or ()) if not r._dead]
    lane_execs = list(getattr(pipe, "_lane_execs", None) or ())
    nodes = list(pipe.elements) + regions + lane_execs

    def node_id(el) -> str:
        return f"n{id(el):x}"

    in_region = {id(m) for r in regions for m in r.members}
    for el in pipe.elements:
        if id(el) in in_region:
            continue
        lines.append(
            f'  {node_id(el)} [label="{_esc(el.name)}\\n'
            f'({_esc(el.ELEMENT_NAME)})"];')
    for r in regions:
        lines.append(f"  subgraph cluster_{node_id(r)} {{")
        lines.append(f'    label="{_esc(r.name)}\\n(fused region — one '
                     f'XLA program)"; style=dashed; color=blue;')
        for m in r.members:
            lines.append(
                f'    {node_id(m)} [label="{_esc(m.name)}\\n'
                f'({_esc(m.ELEMENT_NAME)})"];')
        lines.append("  }")
        # the region itself: a small routing node so external links render
        lines.append(
            f'  {node_id(r)} [label="{_esc(r.name)}" shape=cds '
            f"color=blue];")
    for ex in lane_execs:
        # the ingest lane executor spliced between source and the rest of
        # the graph (pipeline/lanes.py): a routing node like the regions',
        # plus a dashed edge to the template segment it replicates per lane
        lines.append(
            f'  {node_id(ex)} [label="{_esc(ex.name)}\\n'
            f'({ex.n} ingest lanes)" shape=cds color=darkgreen];')
        if ex.segment:
            lines.append(
                f"  {node_id(ex)} -> {node_id(ex.segment[0])} "
                f'[label="replicates ×{ex.n}" style=dashed '
                f"color=darkgreen];")
    for el in nodes:
        for sp in el.srcpads:
            peer = sp.peer
            if peer is None:
                continue
            label = _caps_label(sp)
            attr = f' [label="{label}"]' if label else ""
            lines.append(
                f"  {node_id(el)} -> {node_id(peer.element)}{attr};")
    lines.append("}")
    return "\n".join(lines) + "\n"


def maybe_dump_dot(pipe, phase: str = "playing") -> str | None:
    """Write a dot dump if ``NNSTPU_DUMP_DOT_DIR`` is set; returns the
    path written (or None). Failures only warn — a dump must never take
    down the pipeline."""
    out_dir = os.environ.get("NNSTPU_DUMP_DOT_DIR", "").strip()
    if not out_dir:
        return None
    from nnstreamer_tpu.log import get_logger

    try:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"{next(_serial):04d}-{pipe.name}.{phase}.dot")
        with open(path, "w") as f:
            f.write(pipeline_to_dot(pipe))
        return path
    except Exception as e:  # noqa: BLE001 — a debugging aid must never
        # abort Pipeline.start(): encoding errors, odd node attributes,
        # and filesystem failures all just warn
        get_logger("dot").warning("dot dump failed: %s", e)
        return None
