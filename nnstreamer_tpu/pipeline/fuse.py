"""Region fusion — compile chains of device-capable elements into ONE XLA
program.

The reference's per-element hot path is a C function call per element per
frame (tensor_filter.c:547, tensor_transform.c chain); cheap on a CPU, but
on a TPU every element-level dispatch is a host→device round trip. The
TPU-first answer (SURVEY §7 design stance: "the pipeline graph compiles
region-wise into jitted XLA programs") is this pass: after elements start,
maximal runs of *fusible* single-in/single-out elements are re-linked behind
a :class:`FusedRegion` whose chain performs a single ``jax.jit`` dispatch.
XLA then fuses the whole run — e.g. uint8 frame → normalize → MobileNet →
logits becomes one executable with one H2D transfer per frame.

An element opts in by implementing ``device_stage() -> DeviceStage | None``:
a pure, shape-polymorphic ``fn(consts, tensors) -> tensors`` plus the
device-resident constants (model params) passed as jit arguments (NOT
captured, so hot model reload swaps params without recompiling). Elements
whose per-frame behavior is host-side control flow (throttling drops, sync
policies, routing) simply don't implement it and stay unfused.

Disable globally with ``NNSTPU_FUSE=0`` or per-pipeline with
``Pipeline(fuse=False)``.
"""

from __future__ import annotations

import dataclasses
import os
import time as _time
import warnings
from typing import Any, Callable, List, Optional, Sequence, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.parallel import serve as _serve
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.pipeline.element import (
    CustomEvent,
    Element,
    Event,
    FlowError,
    Pad,
    peer_device_capable,
)
from nnstreamer_tpu.pipeline.supervise import effective_policy
from nnstreamer_tpu.tensors.buffer import (
    H2D_EXCLUSIVE_META,
    as_device_buffer,
    is_device_array,
)

log = get_logger("fuse")

# donation falls back gracefully where XLA can't apply it (host numpy
# inputs, backends without aliasing support): JAX executes correctly and
# warns — the warning is expected steady-state noise here, not a bug
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")
warnings.filterwarnings("ignore", message="Donation is not implemented")

#: error policies under which the supervisor may RE-INVOKE chain() with
#: the same buffer after a fault — a donated input can't be replayed, so
#: these arm a device-side replay copy instead of donating the original
_REPLAY_POLICIES = ("retry", "degrade")


@dataclasses.dataclass
class DeviceStage:
    """One element's contribution to a fused region.

    ``fn(consts, tensors)`` must be traceable by JAX (pure, no data-dependent
    Python control flow) and polymorphic over the number/shape of tensors.
    ``consts`` is any pytree (device arrays preferred); it is threaded
    through the jitted call as an argument so const updates (model reload)
    don't recompile when shapes are unchanged.

    ``key`` identifies the *traced computation* (not the consts): the region
    re-jits only when a member's key changes (model function swapped,
    transform option edited); a rebuild with identical keys just swaps
    consts into the existing executable — no XLA recompile.
    """

    consts: Any
    fn: Callable[[Any, List[Any]], List[Any]]
    key: Any = None
    #: serving-mesh spec (``parallel/serve.py`` grammar) this stage's
    #: consts are placed for — the region adopts it and compiles the
    #: whole-graph program sharded across the mesh. None = single device.
    mesh: Optional[str] = None
    #: optional deferred host completion ``fn(host_buf) -> TensorBuffer``
    #: attached to outgoing buffers (TensorBuffer.finalize) — used by
    #: decoders whose math runs on device but whose output needs host-only
    #: work (label strings, overlay compose). A finalizing stage terminates
    #: its fused run: downstream elements see its *device* tensors only
    #: after a sink materializes them.
    finalize: Optional[Callable] = None


def fusion_enabled() -> bool:
    return os.environ.get("NNSTPU_FUSE", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def donation_enabled() -> bool:
    """Kill switch for input-slab donation (``NNSTPU_DONATE=0``): the
    fused program then never aliases its input buffers, which is the
    reference behavior for debugging donation-suspected corruption."""
    return os.environ.get("NNSTPU_DONATE", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def _single_io(el: Element) -> bool:
    return len(el.sinkpads) == 1 and len(el.srcpads) == 1


def _stage_of(el: Element) -> Optional[DeviceStage]:
    getter = getattr(el, "device_stage", None)
    if getter is None:
        return None
    try:
        return getter()
    except Exception as e:  # noqa: BLE001 — an element that can't stage
        # simply stays unfused; fusion is an optimization, never a failure
        log.debug("element %s not fusible: %s", el.name, e)
        return None


def device_foldable(el: Element) -> bool:
    """Whether this element currently offers a device stage — i.e. whether
    ``fuse_pipeline`` could fold its per-frame math into a region's jitted
    program. The ingest lane planner (``pipeline/lanes.py``) consults this
    to report the device-side preprocessing preamble: a stage-capable
    ``tensor_transform`` adjacent to a filter runs inside the fused region
    (zero host math in the lanes) when fusion is on, and stays host-side
    lane work when it is off."""
    return _single_io(el) and _stage_of(el) is not None


class FusedRegion(Element):
    """Replaces a run of fusible elements with one jitted dispatch.

    The member elements stay in the pipeline (their properties, stats and
    custom-event handling remain live); only their pads are re-routed so
    buffers flow through this region instead. Caps negotiation chains the
    members' own ``transform_caps`` so negotiation semantics are identical
    to the unfused pipeline. Custom events are delivered into the member
    chain (internal links are kept); whatever the members do NOT consume
    reaches this region's internal return pad and is forwarded downstream —
    identical consume semantics to the unfused graph.
    """

    ELEMENT_NAME = "fused_region"
    #: a queue feeding a region may hand its whole backlog as one list —
    #: each buffer dispatches immediately (async), the dispatch window
    #: paces the batch, so a backlog becomes back-to-back device work
    HANDLES_LIST = True
    #: the jitted program consumes jax.Arrays directly — a DeviceBuffer
    #: input skips H2D staging and the ingest pool entirely
    DEVICE_PASSTHROUGH = True
    #: the jitted program may DONATE an incoming single-consumer payload
    #: (upload points mark those with H2D_EXCLUSIVE_META); chain() stages
    #: a replay copy whenever the original must survive a re-invoke
    DONATION_CONSUMER = True
    PROPERTIES = {**Element.PROPERTIES, "inflight": 2}

    def __init__(self, members: Sequence[Element], name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        #: receives whatever flows out of the last member (events only —
        #: buffers no longer flow through members)
        self.internal_pad = self.add_sink_pad("fused-internal")
        self.members: List[Element] = list(members)
        #: (consts_list, jitted, finalize) — swapped atomically; readers
        #: take one local reference so invalidate() can never half-update it
        self._compiled: Optional[Tuple[list, Callable, Optional[Callable]]] \
            = None
        #: (keys_list, jitted) from the last trace — reused when a rebuild
        #: finds identical keys, so consts-only changes never recompile
        self._trace_cache: Optional[Tuple[list, Callable]] = None
        self._dead = False  # set when un-spliced back out of the graph
        self._verified = False  # first frame after a (re)compile is synced
        from nnstreamer_tpu.pipeline.dispatch import DispatchWindow

        #: bounded async dispatch: up to `inflight` outstanding batches
        #: (pipeline/dispatch.py); the region adopts the largest member
        #: `inflight` so `tensor_filter inflight=K` in a description
        #: keeps meaning after fusion
        member_inflight = [int(m.get_property("inflight"))
                           for m in self.members if "inflight" in m._props]
        if member_inflight:
            self._props["inflight"] = max(member_inflight)
        self._window = DispatchWindow(self)
        self._m_retrace = None  # region re-trace counter (lazy)
        self._m_whole = None    # whole-graph program gauge (lazy)
        self._donating = False  # the live jit was built with donation
        #: serving MeshPlan adopted from the members' mesh= specs (set by
        #: _build); None = single-device program
        self._mesh_plan = None

    # -- stage (re)build -----------------------------------------------------
    def _build(self) -> Tuple[list, Callable]:
        import jax

        stages = []
        for m in self.members:
            st = _stage_of(m)
            if st is None:
                raise FlowError(
                    f"fused region {self.name}: member {m.name} is no "
                    f"longer fusible"
                )
            stages.append(st)
        # mesh adoption: a member carrying a mesh= spec asks the WHOLE
        # region to compile sharded across that mesh. One program has one
        # mesh — mixed specs inside a run are a hard plan-time error (NOT
        # a FlowError: silently unsplicing to per-element dispatch would
        # hide a sharding contract violation)
        specs = sorted({st.mesh for st in stages if st.mesh is not None})
        if len(specs) > 1:
            raise _serve.MeshShardingError(
                f"fused region {self.name}: members carry mixed mesh specs "
                f"{specs}; align the mesh= properties or split the run "
                f"with a non-fusible element")
        plan = _serve.get_mesh_plan(specs[0]) \
            if specs and _serve.mesh_enabled() else None
        self._mesh_plan = plan
        stage_keys = [st.key for st in stages]
        # the mesh spec is part of the traced computation's identity: the
        # same member fns compile to a different XLA program per mesh
        keys = stage_keys + [("mesh", plan.spec if plan is not None else "")]
        cache = self._trace_cache
        # a None key means "cannot prove the computation is unchanged" —
        # never match it against the cache
        if any(k is None for k in stage_keys):
            cache = None
        if cache is not None and cache[0] == keys:
            jitted = cache[1]
        else:
            fns = [st.fn for st in stages]
            count = self._count_retrace

            def composed(consts, tensors):
                # the counter fires at TRACE time: jax.jit re-executes
                # this Python body once per distinct input signature, so
                # a new batch shape (aggregator flush tail vs full
                # window) is counted as the real XLA compile it is —
                # while the jit object below is REUSED across shapes, so
                # alternating batch sizes hit jit's per-shape executable
                # cache instead of retracing every frame
                count()
                for f, c in zip(fns, consts):
                    tensors = f(c, list(tensors))
                return list(tensors)

            # donate the input tensor slab: the whole-graph program may
            # write its outputs into the (freshly uploaded, single-
            # consumer) input buffers instead of allocating, and the
            # dead inputs free at dispatch rather than at GC. chain()
            # substitutes a device-side replay copy whenever the
            # original must survive (unverified first frame, armed
            # retry/degrade policy, non-exclusive payload).
            # under a mesh plan this same jit IS the whole-graph SHARDED
            # program: chain() places inputs batch-sharded over dp
            # (serve.place_batch) and GSPMD propagates that sharding
            # through to the outputs — for leading-dim batch sharding
            # the propagation is exact, so the hand-off into a
            # downstream region on the same mesh is matched and moves
            # zero bytes. No sharding is CONSTRUCTED here (NNS117);
            # pinning out_shardings instead would reject the ragged
            # batches (flush tails) that place_batch runs replicated.
            jitted = jax.jit(composed, donate_argnums=(1,)) \
                if donation_enabled() else jax.jit(composed)
            self._trace_cache = (keys, jitted)
            self._donating = donation_enabled()
        compiled = ([st.consts for st in stages], jitted, stages[-1].finalize)
        self._compiled = compiled
        if self._m_whole is None:
            import weakref

            from nnstreamer_tpu.obs import get_registry

            ref = weakref.ref(self)

            def _whole() -> float:
                r = ref()
                return 1.0 if (r is not None and r._compiled is not None
                               and r._compiled[2] is not None) else 0.0

            self._m_whole = get_registry().gauge(
                "nns_fuse_whole_graph",
                "1 when this region's single jitted program covers the "
                "whole device-decodable graph (finalizing decoder stage "
                "folded in: no mid-stream D2H, host-only work deferred "
                "to the sink's fetch point)",
                fn=_whole, **self._obs_labels())
        self._verified = False  # first frame after (re)compile syncs
        return compiled

    def _count_retrace(self) -> None:
        """Count actual region re-traces (`nns_fuse_retraces_total`) —
        the no-new-XLA-recompiles acceptance gate reads this: a consts
        swap or an inflight change must NOT move it."""
        if self._m_retrace is None:
            from nnstreamer_tpu.obs import get_registry

            self._m_retrace = get_registry().counter(
                "nns_fuse_retraces_total",
                "Region re-traces (each implies one XLA compile)",
                **self._obs_labels())
        self._m_retrace.inc()

    def obs_snapshot(self):
        out = super().obs_snapshot()
        out.update(self._window.snapshot())
        if self._m_retrace is not None:
            out["retraces"] = int(self._m_retrace.value)
        return out

    def invalidate(self) -> None:
        """Drop the compiled (consts, jit) pair; the next frame re-pulls
        member stages. Whether that re-traces is decided by stage keys — a
        params-only model reload keeps the executable and just swaps consts;
        a swapped model function / edited transform option re-jits."""
        self._compiled = None

    def start(self):
        super().start()
        if self._dead:
            return
        # members were restarted (backends re-opened, possibly with changed
        # properties) — never reuse a program traced over the old backend
        self.invalidate()
        try:
            self._build()
        except FlowError:
            # a member stopped being fusible (properties changed while the
            # pipeline was NULL) — fall back to the original element links
            self.unsplice()

    # -- negotiation ---------------------------------------------------------
    def transform_caps(self, pad, caps):
        for m in self.members:
            out = m.transform_caps(m.sinkpads[0], caps)
            if out is None:
                return None
            caps = out
        return caps

    # -- hot path ------------------------------------------------------------
    def chain(self, pad, buf):
        if pad is self.internal_pad:
            raise FlowError(f"{self.name}: buffer on internal event pad")
        if self._qos_throttled():
            return None  # downstream-rate QoS drop (tensor_filter.c:426)
        fi = _faults.ACTIVE
        # the device span starts HERE, before the chaos hook: an injected
        # filter.invoke stall models a slow backend invoke, and the flight
        # recorder's variance attribution must see that time in the
        # "device" stage (the span ends before _window.admit so a full
        # window's fence shows up as fence_wait, not double-counted here)
        t_dev0 = _time.monotonic()
        if fi is not None:
            # chaos hook — the same `filter.invoke` site the unfused
            # filter checks (its chain doesn't run while fused), BEFORE
            # donation and the stash pop: a retrying error policy
            # re-enters chain with the buffer fully intact
            fi.check("filter.invoke",
                     seq=buf.meta.get(_timeline.TRACE_SEQ_META))
        compiled = self._compiled
        if compiled is None:
            try:
                compiled = self._build()
            except FlowError:
                # a member stopped being fusible mid-stream (e.g. throttle
                # enabled at runtime) — the unfused pipeline's behavior
                # resumes seamlessly
                return self._fallback(buf)
        consts, jitted, finalize = compiled
        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META

        # upload points stamp single-consumer payloads; popped so the
        # marker never rides through to this region's OUTPUT buffer
        exclusive = bool(buf.meta.pop(H2D_EXCLUSIVE_META, False))
        stash = buf.meta.pop(POOL_STASH_META, None)
        args = list(buf.tensors)
        plan = self._mesh_plan
        t_sh1 = t_dev0
        if plan is not None:
            # mesh placement BEFORE the donation decision: an input
            # already carrying the plan's batch sharding (the matched
            # hand-off from an upstream sharded region) passes through
            # untouched — zero bytes, nns_reshard_bytes_total unmoved;
            # host arrays scatter over dp. Under a mesh the pre-dispatch
            # segment (placement, plus any injected invoke stall above)
            # attributes to the "shard" span, and "device" starts here —
            # the two stages still tile the frame's end-to-end time.
            args = [_serve.place_batch(t, plan) for t in args]
            t_sh1 = _time.monotonic()
        if self._donating and not (
                exclusive and self._verified
                and effective_policy(self) not in _REPLAY_POLICIES):
            # the jitted program donates (consumes) its input slab. Keep
            # the ORIGINALS alive by donating device-side replay copies
            # instead whenever the inputs may be touched again: an armed
            # retry/degrade policy re-invokes chain() with this same
            # buffer after a fault; an unverified first frame may fall
            # back to the member chain; a non-exclusive payload (source-
            # owned, tee'd) has readers this region can't see. Host
            # numpy inputs need no copy — XLA can't alias them, so
            # donation is a no-op for them.
            args = [t.copy() if is_device_array(t) else t for t in args]
        try:
            out = jitted(consts, args)
            if not self._verified:
                import jax
                # JAX dispatch is asynchronous: a data-dependent RUNTIME
                # failure would otherwise surface later at materialization
                # (sink to_host) as a pipeline error instead of here. Sync
                # the first frame after every (re)compile so both trace-time
                # and first-frame runtime failures take the fallback path;
                # steady-state frames stay fully async.
                # one-time post-(re)compile verification sync, not a
                # per-frame fence; steady-state frames skip this branch
                jax.block_until_ready(out)  # nns-lint: disable=NNS107 -- once
                self._verified = True
        except Exception as e:  # noqa: BLE001  # nns-lint: disable=NNS111 -- falls back to the member chain, whose error handling is authoritative
            # fusion is an optimization,
            # never a failure: a stage that won't trace or whose first
            # post-compile execution fails falls back to the member chain,
            # whose own error handling is authoritative. (Runtime failures
            # on later frames surface at materialization like any other
            # pipeline error.)
            log.warning("%s: fused program failed (%s); falling back to "
                        "member chain", self.name, e)
            return self._fallback(buf)
        tl = _timeline.ACTIVE
        if tl is not None:
            seq = buf.meta.get(_timeline.TRACE_SEQ_META)
            if seq is not None:
                if plan is not None:
                    tl.span("shard", seq, t_dev0, t_sh1, track=self.name)
                tl.span("device", seq, t_sh1, _time.monotonic(),
                        track=self.name)
        # bounded async dispatch: register the outstanding batch (fences
        # the OLDEST only when more than `inflight` are in flight); the
        # pooled host staging arrays this dispatch consumed recycle at
        # that fence point
        self._window.admit(out, stash)
        out_buf = buf.with_tensors(list(out))
        if plan is not None:
            # stamp which serving plan produced these (NamedSharding-
            # carrying) arrays — downstream consumers and dumps can read
            # the spec without touching the device data
            out_buf.meta[_serve.MESH_SPEC_META] = plan.spec
        if finalize is not None:
            out_buf = out_buf.replace(finalize=finalize)
        if peer_device_capable(self.srcpad):
            # downstream forwards resident buffers — emit a DeviceBuffer so
            # region→queue→region chains cross zero host copies (a
            # non-capable peer gets the plain buffer and materializes at
            # its own pace, exactly the pre-residency behavior)
            out_buf = as_device_buffer(out_buf)
        return self.srcpad.push(out_buf)

    def _fallback(self, buf):
        """Restore the original element links and replay ``buf`` (and all
        future buffers) through the member chain."""
        self.unsplice()
        first = self.members[0]
        return first._chain_entry(first.sinkpads[0], buf)

    def handle_eos(self):
        # EOS flush: every outstanding dispatch fences before EOS crosses
        # downstream — a sink observing EOS has all results materializable
        self._window.drain()

    def stop(self):
        self._window.drain()
        super().stop()

    # -- events --------------------------------------------------------------
    def src_event(self, pad: Pad, event: Event) -> None:
        from nnstreamer_tpu.pipeline.element import QosEvent

        if isinstance(event, QosEvent) and any(
                type(m).src_event is not Element.src_event
                for m in self.members):
            # a member consumes QoS (the filter): the event targets THIS
            # region's dispatch, since the members' chains don't run.
            # Deliver through the member chain too, so per-member QoS
            # state stays correct if the region later unsplices, and stop
            # — exactly one throttle gates the stream.
            self._qos_interval_s = event.target_interval_ns / 1e9
            last = self.members[-1]
            last._upstream_event_entry(last.srcpads[0], event)
            return
        # no consuming member: pass upstream past the region via the data
        # sink pad only (the base default would also loop the internal
        # pad, re-dispatching the event into the member chain)
        self.sinkpads[0].push_upstream_event(event)

    def sink_event(self, pad: Pad, event: Event) -> None:
        if pad is self.internal_pad:
            # an event the member chain chose to forward — pass it on
            self.srcpad.push_event(event)
            return
        if isinstance(event, CustomEvent):
            # deliver through the member chain; members that consume it
            # (e.g. tensor_filter eats reload_model) stop it there, others
            # forward it to the internal pad which sends it downstream
            self.members[0]._event_entry(self.members[0].sinkpads[0], event)
            self.invalidate()
            return
        from nnstreamer_tpu.pipeline.element import EosEvent

        if isinstance(event, EosEvent):
            # the internal event pad never sees EOS, so the base "all sink
            # pads at EOS" rule would deadlock — the data sink pad alone
            # decides here
            self.handle_eos()
            self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)

    def __repr__(self):
        names = "+".join(m.name for m in self.members)
        return f"<FusedRegion [{names}]>"

    # -- splicing ------------------------------------------------------------
    def splice(self, pipe) -> None:
        self.pipeline = pipe
        for m in self.members:
            m._fused_region = self  # so member-level mutators (e.g.
            # TensorFilter.reload_model) can invalidate the compiled region
        first, last = self.members[0], self.members[-1]
        up_src = first.sinkpads[0].peer
        down_sink = last.srcpads[0].peer
        if up_src is not None:
            up_src.unlink()
            up_src.link(self.sinkpad)
        if down_sink is not None:
            last.srcpads[0].unlink()
            self.srcpad.link(down_sink)
        # route member-chain event outflow back through this region
        last.srcpads[0].link(self.internal_pad)
        log.info("fused region: %s", self)

    def unsplice(self) -> None:
        """Restore the original element links (region becomes inert)."""
        self._window.drain()  # outstanding dispatches belong to the dying
        # region; fence them so fallback replay can never reorder results
        first, last = self.members[0], self.members[-1]
        last.srcpads[0].unlink()  # internal pad
        up_src = self.sinkpad.peer
        down_sink = self.srcpad.peer
        if up_src is not None:
            up_src.unlink()
            up_src.link(first.sinkpads[0])
        if down_sink is not None:
            self.srcpad.unlink()
            last.srcpads[0].link(down_sink)
        for m in self.members:
            m._fused_region = None
        self._dead = True
        log.info("unspliced region: %s", self)


def fuse_pipeline(pipe) -> List[FusedRegion]:
    """Find maximal fusible runs and splice FusedRegions into the graph.

    Must run after non-source elements started (filter backends open their
    models in start(), and a backend is what makes a filter fusible) and
    before sources begin pushing.
    """
    regions: List[FusedRegion] = []
    in_run = set()
    stage_cache: dict = {}

    def stage_of(el):
        if id(el) not in stage_cache:
            stage_cache[id(el)] = _stage_of(el)
        return stage_cache[id(el)]

    for el in pipe.elements:
        if id(el) in in_run or not _single_io(el):
            continue
        head_stage = stage_of(el)
        if head_stage is None:
            continue
        up = el.sinkpads[0].peer.element if el.sinkpads[0].peer else None
        if up is not None and _single_io(up):
            up_stage = stage_of(up)
            # upstream fusible and able to extend → el is not a run head;
            # a finalizing upstream terminates its own run, so el IS a head
            if up_stage is not None and up_stage.finalize is None:
                continue
        run = [el]
        cur = el
        # a finalizing stage ends its run — nothing can fuse after it
        while stage_of(cur).finalize is None:
            peer = cur.srcpads[0].peer
            nxt = peer.element if peer else None
            if nxt is None or not _single_io(nxt) or stage_of(nxt) is None:
                break
            run.append(nxt)
            cur = nxt
        if len(run) < 2:
            continue
        for m in run:
            in_run.add(id(m))
        region = FusedRegion(run, name="+".join(m.name for m in run))
        region.splice(pipe)
        regions.append(region)
    return regions


# --------------------------------------------------------------------------
# plan-time matched-sharding verification (parallel/serve.py contract)
# --------------------------------------------------------------------------
def _element_mesh_spec(el) -> Optional[str]:
    """The serving-mesh spec this element invokes under, or None. Covers
    sharded fused regions (``_mesh_plan`` from _build) and UNFUSED
    tensor_filters whose backend holds a plan (e.g. the budgeted-weights
    invoke path, which region fusion deliberately skips)."""
    plan = getattr(el, "_mesh_plan", None)
    if plan is None:
        plan = getattr(getattr(el, "fw", None), "_mesh_plan", None)
    return plan.spec if plan is not None else None


def verify_mesh_boundaries(pipe) -> None:
    """PLAN-time check of the matched-sharding contract: every device-
    passthrough hand-off between two mesh-sharded invokers must carry
    identical mesh specs, so the producer's out-sharding equals the
    consumer's in-sharding and the hand-off moves ZERO bytes. A mismatch
    raises :class:`~nnstreamer_tpu.parallel.serve.MeshShardingError`
    before any frame flows — a silent runtime reshard of every frame is
    exactly the performance bug the ``mesh=`` property exists to prevent.
    (Hand-offs that cross a non-passthrough element materialize to host
    anyway and are exempt: that boundary's cost is already explicit.)

    Runs in ``Pipeline.start()`` after regions compile; inert when no
    element carries a mesh plan or ``NNSTPU_MESH=0``.
    """
    if not _serve.mesh_enabled():
        return
    producers = []
    for el in _live_invokers(pipe):
        spec = _element_mesh_spec(el)
        if spec is not None:
            producers.append((el, spec))
    for el, spec in producers:
        for pad in el.srcpads:
            _walk_boundary(el, spec, pad, set())


def _live_invokers(pipe):
    """Pipeline elements buffers actually flow through: added elements
    minus fused members, plus the spliced regions themselves (regions
    live in ``pipe._regions``, not ``pipe.elements``)."""
    for el in getattr(pipe, "elements", []):
        if getattr(el, "_fused_region", None) is not None:
            continue  # fused member: its pads are re-routed
        yield el
    for r in (getattr(pipe, "_regions", None) or ()):
        if not getattr(r, "_dead", False):
            yield r


def pipeline_shard_count(pipe) -> int:
    """Largest serving-mesh fan-out any invoker in the pipeline runs
    under (1 = single device) — the SLO scheduler aligns its admission
    batch cap to a multiple of this so every admitted micro-batch splits
    evenly over dp shards."""
    n = 1
    for el in _live_invokers(pipe):
        plan = getattr(el, "_mesh_plan", None)
        if plan is None:
            plan = getattr(getattr(el, "fw", None), "_mesh_plan", None)
        if plan is not None:
            n = max(n, int(plan.shard_count))
    return n


def _walk_boundary(producer, spec: str, pad: Pad, seen: set) -> None:
    peer = pad.peer
    if peer is None:
        return
    el = peer.element
    if id(el) in seen:
        return
    seen.add(id(el))
    consumer_spec = _element_mesh_spec(el)
    if consumer_spec is not None:
        if consumer_spec != spec:
            raise _serve.MeshShardingError(
                f"mesh boundary {producer.name} -> {el.name}: producer "
                f"shards over mesh={spec!r} but consumer expects "
                f"mesh={consumer_spec!r} — the hand-off would reshard "
                f"every frame; align the mesh= properties (or break "
                f"residency with a non-device-passthrough element to "
                f"make the host bounce explicit)")
        return  # matched; the consumer's own outputs get their own walk
    if not getattr(el, "DEVICE_PASSTHROUGH", False):
        return  # materializes to host — no device hand-off past here
    for p in el.srcpads:
        _walk_boundary(producer, spec, p, seen)
