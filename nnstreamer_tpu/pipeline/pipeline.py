"""Pipeline — element container, scheduler, and bus.

The reference's pipelines are GStreamer pipelines: sources run streaming
threads, ``queue`` elements decouple stages, a bus carries ERROR/EOS messages
to the application. This module provides the same capability:

- :class:`Pipeline` holds elements, drives state changes
  (NULL→READY→PLAYING, reference state model), runs one thread per source
  element, and exposes a bus (:meth:`pop_message`, :meth:`wait`).
- :class:`SourceElement` is the push-mode live/file source base
  (GstBaseSrc's create-loop, e.g. tensor_src_iio.c:18-52).
- :class:`Queue` is the explicit thread boundary (gst ``queue``): a bounded
  buffer + worker thread giving pipeline (stage) parallelism — the
  reference's only intra-pipeline parallelism form (SURVEY §2.4.1). Stages
  separated by queues overlap host work with XLA's async device dispatch.
"""

from __future__ import annotations

import enum
import heapq
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import get_registry, register_pipeline_collector
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.pipeline.element import (
    Element,
    EosEvent,
    Event,
    FlowError,
    FlowReturn,
    Pad,
)
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors import memory as _memory
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("pipeline")


class State(enum.Enum):
    NULL = "null"
    READY = "ready"
    PLAYING = "playing"


class Message:
    """Bus message (GstMessage equivalent)."""

    def __init__(self, kind: str, source: Optional[Element] = None,
                 error: Optional[Exception] = None,
                 text: Optional[str] = None):
        self.kind = kind  # "eos" | "error" | "warning"
        self.source = source
        self.error = error
        self.text = text  # human-readable detail (warnings)

    def __repr__(self):
        detail = f", text={self.text!r}" if self.text else ""
        return (f"Message({self.kind}, "
                f"src={getattr(self.source, 'name', None)}, "
                f"err={self.error}{detail})")


class SourceElement(Element):
    """Push-mode source: the pipeline runs :meth:`create` in a loop on a
    dedicated streaming thread until it returns None (EOS) or the pipeline
    stops."""

    ELEMENT_NAME = "source"
    PROPERTIES = {**Element.PROPERTIES}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.srcpads:
            self.add_src_pad("src")
        self._stop_evt = threading.Event()

    def create(self) -> Optional[TensorBuffer]:
        """Produce the next buffer, or None at end-of-stream. Blocking calls
        must poll ``self._stop_evt``."""
        raise NotImplementedError

    def negotiate(self) -> None:
        """Announce src caps before the first buffer (override)."""

    # -- driven by Pipeline ---------------------------------------------------
    def run_loop(self, pipeline: "Pipeline") -> None:
        try:
            self.negotiate()
            while not self._stop_evt.is_set():
                buf = self.create()
                if buf is None:
                    break
                # capture-time stamp for end-to-end frame latency: sinks
                # measure now-create_t at materialization (the reference
                # self-measures exactly this around its hot path,
                # tensor_filter.c:349-423). appsrc callers may pre-set it.
                if "create_t" not in buf.meta:
                    buf.meta["create_t"] = time.monotonic()
                # frame-ledger trace context (obs/timeline.py): one
                # monotone id per frame, stamped by the single source
                # thread — the same single-writer discipline the lane
                # executor uses for its reorder sequence
                tl = _timeline.ACTIVE
                if tl is not None and \
                        _timeline.TRACE_SEQ_META not in buf.meta:
                    buf.meta[_timeline.TRACE_SEQ_META] = tl.next_seq()
                ret = self.srcpad.push(buf)
                if ret is FlowReturn.EOS:
                    break
            for sp in self.srcpads:
                sp.push_event(EosEvent())
            pipeline.post_message(Message("eos", self))
        except FlowError as e:
            pipeline.post_error(self, e)
        except Exception as e:  # noqa: BLE001 — bus carries any failure
            pipeline.post_error(self, e)

    def stop(self):
        self._stop_evt.set()
        super().stop()


@subplugin(ELEMENT, "queue")
class Queue(Element):
    """Thread-boundary element: bounded FIFO + worker thread.

    ``max_size_buffers`` bounds occupancy; ``leaky`` ("no"|"downstream")
    selects blocking vs drop-oldest backpressure (gst queue's leaky prop).
    """

    ELEMENT_NAME = "queue"
    HANDLES_DEFERRED = True  # pure hand-off: finalize stays lazy across it
    DEVICE_PASSTHROUGH = True  # never reads tensor bytes on the host
    PROPERTIES = {**Element.PROPERTIES, "max_size_buffers": 16, "leaky": "no",
                  "prefetch_host": False, "prefetch_device": False,
                  # stamp_admission: record meta["admitted_t"] when a buffer
                  # is accepted into the FIFO. A leaky ingress queue is the
                  # admission-control point of a saturated pipeline: sinks
                  # report latency from this stamp (base="admitted") so the
                  # saturation-phase p99 measures service time of frames the
                  # pipeline actually served, not the unbounded backlog wait
                  # a free-running source builds before the drop point.
                  "stamp_admission": False,
                  # materialize_host: drain in groups and hand HOST buffers
                  # downstream (one overlapped D2H flush per backlog; the
                  # deferred finalize is applied here). For sink-bound
                  # queues feeding to-host consumers; unlike prefetch_host
                  # it changes the payload type, so it is its own opt-in.
                  "materialize_host": False,
                  # batch drain: max buffers the worker gathers per wake
                  # (whatever is ALREADY queued — it never waits). Runs of
                  # data buffers go to HANDLES_LIST peers as one list;
                  # 1 disables gathering entirely.
                  "drain_batch": 64,
                  # batch_h2d: with prefetch_device, defer the upload to
                  # the drain side and coalesce each gathered run into a
                  # single staged multi-frame slab upload (one pool
                  # window slab, one device_put; per-frame views carved
                  # device-side — tensors/buffer.py upload_many). False
                  # restores the per-frame producer-side to_device path.
                  "batch_h2d": True,
                  # slo_budget_ms: per-queue SLO budget (ms). >0 makes
                  # this queue an admission point of the pipeline's
                  # SloScheduler (serving/scheduler.py): deadline
                  # admission at chain(), EDF ordering instead of FIFO,
                  # late-first shedding on overflow, and batch forming
                  # capped by the feedback controller. 0 (default) with
                  # no pipeline-level budget = the exact pre-scheduler
                  # path (no scheduler object is even built).
                  "slo_budget_ms": 0.0}

    _EOS = object()
    #: worker wake token for scheduler mode — data rides the EDF heap,
    #: the FIFO carries only ordering (tokens/events/EOS)
    _TOKEN = object()

    #: rate limit for the leaky-drop warning (seconds between warnings)
    DROP_WARN_INTERVAL_S = 5.0

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._q: _queue.Queue = _queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._eos_done = threading.Event()
        self._m_drops = None      # leaky-downstream drop counter (lazy)
        self._m_blocked = None    # cumulative blocked-put seconds (lazy)
        self._m_drain = None      # per-wake drain size histogram (lazy)
        #: data buffers the worker has popped but not yet handed
        #: downstream — batch drain moves the backlog out of the FIFO in
        #: one wake, so qsize() alone would under-report occupancy while
        #: the worker is blocked delivering (single-writer: the worker)
        self._undelivered = 0
        self._last_drop_warn_t = 0.0
        self._drops_since_warn = 0
        #: SLO scheduler binding (serving/scheduler.py), resolved at
        #: start(); None = plain FIFO queue, the kill-switch path
        self._sched = None
        self._budget_ms = 0.0
        self._edf: list = []          # (deadline_t, seq, buf) heap
        self._edf_lock = threading.Lock()
        self._edf_seq = 0             # FIFO tiebreak for equal deadlines
        self._m_admitted = None       # stamp_admission accept counter
        self._m_adm_revoked = None    # admitted-then-dropped counter

    def _obs_init(self):
        """Queue metrics: depth gauge (sampled), drop counter, blocked
        time. Created at start() so the labels carry the owning
        pipeline's name."""
        reg = get_registry()
        labels = self._obs_labels()
        self._m_drops = reg.counter(
            "nns_queue_drops_total",
            "Buffers discarded by leaky=downstream backpressure", **labels)
        self._m_blocked = reg.counter(
            "nns_queue_blocked_seconds_total",
            "Cumulative producer time spent blocked on a full queue",
            **labels)
        self._m_drain = reg.histogram(
            "nns_queue_drain_size",
            "Data buffers the worker drained per wake (backlog batching)",
            buckets=(1, 2, 4, 8, 16, 32, 64), **labels)
        self._m_admitted = reg.counter(
            "nns_queue_admitted_total",
            "Buffers accepted at a stamp_admission point", **labels)
        self._m_adm_revoked = reg.counter(
            "nns_queue_admitted_revoked_total",
            "Admitted buffers later dropped before delivery (the "
            "admitted population nets these out)", **labels)
        import weakref

        ref = weakref.ref(self)
        reg.gauge("nns_queue_depth", "Buffers currently queued",
                  fn=lambda: (ref()._depth() if ref() is not None else 0),
                  **labels)

    def _count_drop(self) -> None:
        """Satellite: leaky-downstream drops were silent — count every
        one and emit one rate-limited warning so live operators see the
        loss without per-frame log spam."""
        self._m_drops.inc()
        self._drops_since_warn += 1
        now = time.monotonic()
        if now - self._last_drop_warn_t >= self.DROP_WARN_INTERVAL_S:
            self.log.warning(
                "%s: leaky=downstream dropped %d buffer(s) since last "
                "report (downstream slower than producer; total %d)",
                self.name, self._drops_since_warn,
                int(self._m_drops.value))
            self._last_drop_warn_t = now
            self._drops_since_warn = 0

    # -- frame-ledger hooks (obs/timeline.py) --------------------------------
    def _tl_arrive(self, buf) -> None:
        """The FIRST queue a frame reaches closes its ingest span
        (source ``create()`` → here, minus any lane reorder wait, so
        ingest + lane_reorder tile exactly); every queue stamps the
        entry time its drain side turns into a queue_wait/sched_hold
        span. No-op (one attr read) with tracing off."""
        tl = _timeline.ACTIVE
        if tl is None:
            return
        seq = buf.meta.get(_timeline.TRACE_SEQ_META)
        if seq is None:
            return
        now = time.monotonic()
        if "tl_ingest_done" not in buf.meta:
            buf.meta["tl_ingest_done"] = True
            create = buf.meta.get("create_t")
            if create is not None:
                reorder = buf.meta.pop("tl_reorder_s", 0.0)
                tl.span("ingest", seq, create,
                        max(now - reorder, create), track="ingest")
        buf.meta["tl_q_t"] = now

    def _tl_depart(self, buf, kind: Optional[str] = None) -> None:
        """Drain-side twin of :meth:`_tl_arrive`: queue residency ends
        when the worker pops the frame. FIFO pops record ``queue_wait``,
        EDF pops ``sched_hold``."""
        tl = _timeline.ACTIVE
        if tl is None:
            return
        t0 = buf.meta.pop("tl_q_t", None)
        if t0 is None:
            return
        seq = buf.meta.get(_timeline.TRACE_SEQ_META)
        if seq is None:
            return
        if kind is None:
            kind = "sched_hold" if self._sched is not None else "queue_wait"
        tl.span(kind, seq, t0, time.monotonic(), track=self.name)

    def _depth(self) -> int:
        """Occupancy: FIFO (or EDF heap in scheduler mode) + popped but
        undelivered."""
        if self._sched is not None:
            with self._edf_lock:
                queued = len(self._edf)
        else:
            queued = self._q.qsize()
        return queued + self._undelivered

    def obs_snapshot(self):
        out = super().obs_snapshot()
        out["depth"] = self._depth()
        if self._m_drops is not None:
            out["drops"] = int(self._m_drops.value)
            out["blocked_s"] = round(self._m_blocked.value, 4)
        if self._m_drain is not None and self._m_drain.count:
            out["drain_size_p50"] = self._m_drain.percentile(50)
        return out

    def start(self):
        super().start()
        self._stop_evt.clear()
        self._eos_done.clear()
        self._undelivered = 0
        # scheduler binding: this queue is an admission point when the
        # pipeline has an SloScheduler AND this queue either stamps
        # admission or carries its own budget. No scheduler (budget
        # unset anywhere) = the exact pre-scheduler FIFO path.
        own_budget = float(self.get_property("slo_budget_ms") or 0.0)
        sched = getattr(self.pipeline, "_slo_scheduler", None)
        if sched is not None and (own_budget > 0
                                  or self.get_property("stamp_admission")):
            self._sched = sched
            self._budget_ms = own_budget if own_budget > 0 \
                else sched.budget_ms
        else:
            self._sched = None
        if self._sched is not None:
            # data rides the EDF heap (bounded by max_size_buffers in
            # _chain_scheduled); the FIFO carries only wake tokens and
            # serialized events, so it must never block a producer
            self._edf = []
            self._edf_seq = 0
            self._q = _queue.Queue()
        else:
            self._q = _queue.Queue(
                maxsize=int(self.get_property("max_size_buffers")))
        if self._m_drops is None:
            self._obs_init()
        self._worker = threading.Thread(
            target=self._drain_sched if self._sched is not None
            else self._drain,
            name=f"{self.name}-worker", daemon=True
        )
        self._worker.start()

    def stop(self):
        self._stop_evt.set()
        try:
            self._q.put_nowait(self._EOS)
        except _queue.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout=5)
            self._worker = None
        super().stop()

    def accepts_now(self) -> bool:
        """True when a push would be absorbed without blocking/dropping.
        Latency-budget upstreams (aggregator latency-budget-ms) poll
        this before flushing a partial window early: when the pipeline
        is backed up, holding the window (letting it fill toward a full
        batch) beats stacking more dispatches onto a saturated link."""
        if self._worker is None:
            return True
        if self._sched is not None:
            with self._edf_lock:
                return len(self._edf) < \
                    int(self.get_property("max_size_buffers"))
        maxsize = self._q.maxsize
        return maxsize <= 0 or self._q.qsize() < maxsize

    def chain(self, pad, buf):
        fi = _faults.ACTIVE
        if fi is not None:
            # chaos hook (pipeline/faults.py): a raise here surfaces
            # through _chain_entry under THIS queue's error policy
            fi.check("queue.push",
                     seq=buf.meta.get(_timeline.TRACE_SEQ_META))
        if self.get_property("prefetch_host") and \
                not self.get_property("materialize_host"):
            # (materialize_host issues the copies drain-side, grouped)
            # start D2H for device tensors NOW (producer side) so a
            # downstream to_host consumer finds the copy already in flight
            # instead of serializing one device round trip per frame
            for t in buf.tensors:
                start_async = getattr(t, "copy_to_host_async", None)
                if start_async is not None:
                    start_async()
        if self.get_property("prefetch_device"):
            # batch_h2d defers the upload to the drain worker, which
            # coalesces each gathered run into ONE staged window upload
            # (_upload_run); the worker thread still overlaps the
            # transfer with the producer. Producer-side per-frame upload
            # remains for batch_h2d=false and the degenerate unstarted
            # passthrough (no worker to defer to). A frame the SLO
            # scheduler sheds from the EDF heap then never paid its H2D.
            defer = (self.get_property("batch_h2d")
                     and self._worker is not None
                     and not buf.on_device())
            if not defer:
                buf = self._upload_one(buf)
        self._tl_arrive(buf)
        if self._sched is not None and self._worker is not None:
            # SLO path: deadline admission + EDF heap; rejected frames
            # never carry an admission stamp and are dropped here
            return self._chain_scheduled(buf)
        if self.get_property("stamp_admission"):
            if "admitted_t" not in buf.meta:
                buf.meta["admitted_t"] = time.monotonic()
                if self._m_admitted is not None:
                    self._m_admitted.inc()
        if self._worker is None:  # not started: degenerate passthrough
            return self.srcpad.push(buf)
        if self.get_property("leaky") == "downstream":
            while True:
                try:
                    self._q.put_nowait(buf)
                    return FlowReturn.OK
                except _queue.Full:
                    try:
                        dropped = self._q.get_nowait()  # drop oldest
                        self._count_drop()
                        # a frame dropped AFTER stamp_admission leaves
                        # the admitted population: revoke the stamp (a
                        # shared-meta consumer — tee branch, aggregated
                        # window — must not report it as a served-latency
                        # outlier) and count the revocation so admitted
                        # accounting nets out
                        if not (dropped is self._EOS
                                or isinstance(dropped, Event)):
                            if dropped.meta.pop("admitted_t",
                                                None) is not None:
                                self._m_adm_revoked.inc()
                            # the dropped frame never reaches a fence:
                            # release its staged pool slabs / exclusive
                            # device payload now, not at GC
                            from nnstreamer_tpu.pipeline.dispatch import (
                                release_shed_payload,
                            )

                            release_shed_payload(dropped)
                    except _queue.Empty:
                        pass
        else:
            t0 = None
            while not self._stop_evt.is_set():
                try:
                    self._q.put(buf, timeout=0.1)
                    if t0 is not None:
                        self._m_blocked.inc(time.monotonic() - t0)
                    return FlowReturn.OK
                except _queue.Full:
                    if t0 is None:
                        t0 = time.monotonic()
                    continue
            return FlowReturn.EOS

    def sink_event(self, pad, event):
        if self._worker is None:
            super().sink_event(pad, event)
            return
        if isinstance(event, EosEvent):
            # EOS is serialized: enqueue the sentinel in-order, then block
            # until the worker has drained everything ahead of it and
            # forwarded EOS downstream (gst serialized-event semantics).
            self._q.put(self._EOS)
            self._eos_done.wait(timeout=30)
        else:
            # all other events are serialized with the data flow too —
            # a CapsEvent must not overtake buffers queued ahead of it
            self._q.put(event)

    # -- drain-side H2D batching (tensors/buffer.py upload_many) -------------
    def _upload_one(self, buf):
        """Per-frame upload path (producer-side prefetch, window
        singletons, deferred-pad partial windows): to_device + pool
        stash stamp + DeviceBuffer wrap with the pre-upload host view."""
        if not buf.on_device():
            from nnstreamer_tpu.tensors.buffer import as_device_buffer
            from nnstreamer_tpu.tensors.pool import get_pool

            stash = [t for t in buf.tensors if get_pool().owns(t)]
            host_src = list(buf.tensors)
            buf = buf.to_device()
            # the uploaded copy is the payload from here on; the
            # pre-upload host arrays become the wrapper's zero-copy
            # host view (a later to_host costs nothing), and any
            # pool-owned ones are pinned against explicit release
            buf = as_device_buffer(buf, host_view=host_src)
            # freshly uploaded copy with exactly one downstream consumer:
            # a fused region may donate it to XLA (tensors/buffer.py)
            from nnstreamer_tpu.tensors.buffer import H2D_EXCLUSIVE_META

            buf.meta[H2D_EXCLUSIVE_META] = True
            if stash:
                # pooled staging arrays must survive until the
                # dispatch that consumes the uploaded copies has
                # fenced (the H2D may alias or still be in flight);
                # the downstream DispatchWindow releases them at its
                # fence point (pipeline/dispatch.py). to_device()
                # returned a fresh buffer, so its meta is still ours
                # to stamp.
                from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META

                buf.meta[POOL_STASH_META] = stash
        # a latency-budget partial window deferred its padding here
        # (aggregator pad-device): only the real frames crossed the
        # link; the zero rows are synthesized on device now
        if buf.meta.get("pad_rows"):
            buf = buf.pad_rows_device()
        return buf

    def _upload_group(self, group: list) -> list:
        """One staged multi-frame slab upload for ≥2 same-signature host
        buffers. Per-buffer pool stashes are preserved; the window slabs
        the upload staged through ride the LAST buffer's stash — the
        dispatch window fences in order, so by the time the last frame's
        fence releases them every dispatch that read the upload has
        completed (live DeviceBuffer host views keep their slab out of
        circulation via the pool's refcount guard regardless)."""
        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META
        from nnstreamer_tpu.tensors.buffer import upload_many
        from nnstreamer_tpu.tensors.pool import get_pool

        pool = get_pool()
        stashes = [[t for t in b.tensors if pool.owns(t)] for b in group]
        devs, slabs = upload_many(group)
        for b, st in zip(devs, stashes):
            if st:
                b.meta[POOL_STASH_META] = st
        if slabs:
            last = devs[-1]
            last.meta[POOL_STASH_META] = list(
                last.meta.get(POOL_STASH_META) or []) + slabs
        return devs

    def _upload_run(self, run: list) -> list:
        """Split a drained run into maximal groups of consecutive
        host-resident, identically-shaped buffers and upload each group
        as one window slab; singletons, device-resident buffers, and
        deferred-pad partials take the per-frame path."""
        import numpy as _np

        def _single(b) -> bool:
            return (b.on_device() or not b.tensors
                    or b.meta.get("pad_rows")
                    or not all(isinstance(t, _np.ndarray)
                               for t in b.tensors))

        out: list = []
        i = 0
        while i < len(run):
            b = run[i]
            if _single(b):
                out.append(self._upload_one(b))
                i += 1
                continue
            sig = [(t.shape, t.dtype) for t in b.tensors]
            j = i + 1
            while j < len(run) and not _single(run[j]) and \
                    [(t.shape, t.dtype)
                     for t in run[j].tensors] == sig:
                j += 1
            if j - i >= 2:
                out.extend(self._upload_group(run[i:j]))
            else:
                out.append(self._upload_one(b))
            i = j
        return out

    def _flush_run(self, run: list) -> None:
        """Deliver a gathered run of data buffers: materialized one by
        one (materialize_host), as ONE list hand-off when the peer opts
        in (``Pad.push_list`` → ``HANDLES_LIST``), else per-buffer."""
        if not run:
            return
        if self.get_property("prefetch_device") and \
                self.get_property("batch_h2d"):
            # deferred uploads land here: the whole run crosses H2D as
            # one staged slab (buffer identity changes; the timeline/
            # admission meta rides along on the uploaded copies)
            run = self._upload_run(run)
        # queue-residency spans end HERE, per item, right before its
        # hand-off — stamping at drain-pop time would hide the in-batch
        # wait (item N sitting in the drained run while items 0..N-1
        # push through the downstream chain) as unattributed e2e time
        tl_on = _timeline.ACTIVE is not None
        if self.get_property("materialize_host"):
            # materialize HERE, where the group's copies were just
            # issued — handing device arrays onward would re-serialize
            # the fetches at the sink. The whole run comes back in ONE
            # grouped device_get (zero per-frame D2H round trips —
            # d2h_per_frame stays 0 on a device-decodable pipeline);
            # per-buffer finalize/caching semantics match to_host().
            from nnstreamer_tpu.tensors.buffer import materialize_many

            hosts = materialize_many(run)
            for it, host in zip(run, hosts):
                self._undelivered -= 1
                if tl_on:
                    self._tl_depart(it)
                self.srcpad.push(host)
        elif len(run) > 1:
            peer = self.srcpad.peer
            if peer is not None and getattr(peer.element,
                                            "HANDLES_LIST", False):
                # one chain_list hand-off: the whole run leaves at once
                self._undelivered -= len(run)
                if tl_on:
                    for it in run:
                        self._tl_depart(it)
                self.srcpad.push_list(run)
            else:
                # push_list would fall back to sequential pushes — keep
                # the occupancy honest while the peer works through them
                for it in run:
                    self._undelivered -= 1
                    if tl_on:
                        self._tl_depart(it)
                    self.srcpad.push(it)
        else:
            self._undelivered -= 1
            if tl_on:
                self._tl_depart(run[0])
            self.srcpad.push(run[0])

    def _drain(self):
        group_host = bool(self.get_property("materialize_host"))
        drain_max = max(1, int(self.get_property("drain_batch")))
        while not self._stop_evt.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            batch = [item]
            if drain_max > 1 and not isinstance(item, Event) and \
                    item is not self._EOS:
                # gather whatever is ALREADY queued (never wait): one
                # grouped flush services the whole backlog — one worker
                # wake, one downstream hand-off. On a tunneled chip a
                # blocking fetch costs a full RTT (~100 ms) no matter the
                # size, but transfers started from this thread right
                # before the block all ride the same round — A/B-measured
                # 6x per-buffer (94 ms → 16 ms) at depth 10.
                while len(batch) < drain_max:
                    try:
                        nxt = self._q.get_nowait()
                    except _queue.Empty:
                        break
                    batch.append(nxt)
                    if nxt is self._EOS or isinstance(nxt, Event):
                        break  # events stay serialized with the data flow
            ndata = sum(1 for it in batch
                        if it is not self._EOS and not isinstance(it, Event))
            self._undelivered += ndata
            if ndata and self._m_drain is not None:
                self._m_drain.observe(ndata)
            if group_host:
                for it in batch:
                    if isinstance(it, Event) or it is self._EOS:
                        continue
                    for t in it.tensors:
                        start_async = getattr(t, "copy_to_host_async", None)
                        if start_async is not None:
                            start_async()
            run: list = []
            try:
                for it in batch:
                    if it is self._EOS or isinstance(it, Event):
                        # events delimit runs and stay serialized: drain
                        # the data queued ahead of them first
                        self._flush_run(run)
                        run = []
                        if it is self._EOS:
                            self.srcpad.push_event(EosEvent())
                            self._eos_done.set()
                            return
                        self.srcpad.push_event(it)
                    else:
                        run.append(it)
                self._flush_run(run)
            except Exception as e:  # noqa: BLE001 — downstream
                # negotiation or chain failures must reach the bus,
                # not silently kill this worker thread
                self.post_error(e if isinstance(e, FlowError)
                                else FlowError(f"{self.name}: {e}"))
                self._eos_done.set()  # unblock a waiting EOS pusher
                return

    # -- SLO scheduler mode (serving/scheduler.py) ---------------------------
    def _chain_scheduled(self, buf) -> FlowReturn:
        """Producer side of scheduler mode: deadline admission, EDF
        enqueue, late-first shedding on overflow. With a uniform budget
        deadlines are monotone in arrival order, so an unloaded queue's
        pop order equals FIFO — byte-identical output."""
        sched = self._sched
        now = time.monotonic()
        with self._edf_lock:
            backlog = len(self._edf) + self._undelivered
        if not sched.admit(buf, now=now, backlog=backlog,
                           budget_ms=self._budget_ms):
            self._count_drop()
            return FlowReturn.OK  # rejected at the door, never admitted
        if self._m_admitted is not None:
            self._m_admitted.inc()
        cap = int(self.get_property("max_size_buffers"))
        shed = None
        with self._edf_lock:
            self._edf_seq += 1
            heapq.heappush(self._edf,
                           (buf.meta["deadline_t"], self._edf_seq, buf))
            if cap > 0 and len(self._edf) > cap:
                shed = self._shed_one_locked(now)
        if shed is not None:
            sched.note_shed(shed, now)
            self._m_adm_revoked.inc()
            self._count_drop()
        self._q.put_nowait(self._TOKEN)  # wake the worker (unbounded)
        return FlowReturn.OK

    def _shed_one_locked(self, now: float):
        """Pick the overflow victim (caller holds ``_edf_lock``):
        late-first — the MOST-late frame (earliest past deadline, i.e.
        the heap root) sheds before any on-time one; with nothing late
        yet, the least-urgent (latest-deadline) frame goes."""
        if self._edf[0][0] <= now:
            return heapq.heappop(self._edf)[2]
        i = max(range(len(self._edf)), key=lambda j: self._edf[j][0])
        victim = self._edf[i][2]
        last = self._edf.pop()
        if i < len(self._edf):
            self._edf[i] = last
            heapq.heapify(self._edf)
        return victim

    def _flush_edf(self, limit: Optional[int],
                   group_host: bool) -> None:
        """Batch former: pop up to ``limit`` admitted frames in EDF
        order and deliver them as one run (``push_list`` to
        HANDLES_LIST peers — the downstream DispatchWindow's fence is
        the free-slot backpressure: a full window blocks this worker, so
        new batches only form when a dispatch slot frees).

        Frames whose deadline passed while they sat in the heap are
        shed HERE, not delivered: serving them would burn device time on
        work that already missed its SLO and then report the miss as an
        admitted-latency outlier (the EOS flush after a stall was the
        worst case: every parked frame surfaced at once, hundreds of ms
        late). On the sequential hand-off path the deadline is re-tested
        per frame right before its push — a stall INSIDE the run (a slow
        peer, GIL contention) makes frames that were on time when the
        batch formed go late while they wait behind it. A HANDLES_LIST
        peer gets the whole run in one hand-off instead: the frames
        become in-flight together, so there is no serial wait to re-test
        for. An unloaded pipeline never goes late, so the byte-
        identical-to-FIFO contract is untouched."""
        now = time.monotonic()
        shed: list = []
        with self._edf_lock:
            n = len(self._edf) if limit is None \
                else min(max(1, limit), len(self._edf))
            run = []
            while self._edf and len(run) < n:
                deadline_t, _seq, buf = heapq.heappop(self._edf)
                if deadline_t <= now:
                    shed.append(buf)
                else:
                    run.append(buf)
        if run:
            self._undelivered += len(run)
            if self._m_drain is not None:
                self._m_drain.observe(len(run))
            if group_host:
                for it in run:
                    for t in it.tensors:
                        start_async = getattr(t, "copy_to_host_async",
                                              None)
                        if start_async is not None:
                            start_async()
            peer = self.srcpad.peer
            if len(run) > 1 and not group_host and peer is not None \
                    and getattr(peer.element, "HANDLES_LIST", False):
                self._flush_run(run)
            else:
                for it in run:
                    if it.meta["deadline_t"] <= time.monotonic():
                        self._undelivered -= 1
                        shed.append(it)
                        continue
                    self._flush_run([it])
        for buf in shed:
            self._sched.note_shed(buf, time.monotonic())
            self._m_adm_revoked.inc()
            self._count_drop()

    def _drain_sched(self):
        """Scheduler-mode worker: wake tokens pop EDF batches capped by
        the feedback controller; events/EOS flush all pending data first
        (EDF order) so serialized-event semantics hold — an event never
        overtakes data queued ahead of it."""
        group_host = bool(self.get_property("materialize_host"))
        sched = self._sched
        while not self._stop_evt.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            try:
                if item is self._EOS or isinstance(item, Event):
                    self._flush_edf(None, group_host)
                    if item is self._EOS:
                        self.srcpad.push_event(EosEvent())
                        self._eos_done.set()
                        return
                    self.srcpad.push_event(item)
                else:
                    # a shed frame leaves its wake token behind — the
                    # token then pops an empty heap, a cheap no-op
                    self._flush_edf(sched.batch_cap(), group_host)
            except Exception as e:  # noqa: BLE001 — downstream failures
                # must reach the bus, not silently kill this worker
                self.post_error(e if isinstance(e, FlowError)
                                else FlowError(f"{self.name}: {e}"))
                self._eos_done.set()
                return


class Pipeline:
    """Element container + scheduler + bus."""

    def __init__(self, name: str = "pipeline", fuse: bool = True,
                 lanes: int = 1, slo_budget_ms: float = 0.0,
                 error_policy: Optional[str] = None,
                 watchdog_s: float = 0.0):
        self.name = name
        self.elements: List[Element] = []
        self.by_name: Dict[str, Element] = {}
        self.state = State.NULL
        self._bus: _queue.Queue = _queue.Queue()
        self._threads: List[threading.Thread] = []
        self._eos_pending = 0
        self._lock = threading.Lock()
        self._fuse = fuse
        self._regions: Optional[list] = None
        #: requested ingest lane count (pipeline/lanes.py); 1 = serial
        #: path, NNSTPU_LANES env overrides at start time
        self.lanes = lanes
        self._lane_execs: Optional[list] = None
        #: pipeline-wide SLO budget in ms (serving/scheduler.py); >0
        #: activates deadline admission + EDF + feedback control on the
        #: admission-point queues at start(). 0/unset = no scheduler
        #: object at all — the byte-identical pre-scheduler path.
        self.slo_budget_ms = float(slo_budget_ms or 0.0)
        self._slo_scheduler = None
        #: pipeline-default error policy (pipeline/supervise.py);
        #: elements without their own ``error-policy`` property inherit
        #: this. None = ``halt``, the historical fail-fast behavior.
        self.error_policy = error_policy
        #: watchdog deadline in seconds (>0 arms PipelineWatchdog at
        #: start()); NNSTPU_WATCHDOG_S overrides when unset
        self.watchdog_s = float(watchdog_s or 0.0)
        self._watchdog = None
        #: tail-event dump directory for the flight recorder
        #: (obs/flight.py); None defers to NNSTPU_FLIGHT. The recorder
        #: itself is always on unless NNSTPU_FLIGHT=0.
        self.flight_dir: Optional[str] = None
        self._flight = None
        #: serving-continuity checkpoint directory
        #: (pipeline/continuity.py); None defers to NNSTPU_CHECKPOINT.
        #: Unset ⇒ the continuity layer never runs (exact kill switch).
        self.checkpoint_dir: Optional[str] = None
        self._continuity_restored = False
        # export per-element latency/throughput gauges at scrape time
        # (weakref-bound: a collected pipeline unregisters itself)
        register_pipeline_collector(self)

    # -- construction ---------------------------------------------------------
    def add(self, *elements: Element) -> "Pipeline":
        for el in elements:
            if el.name in self.by_name:
                raise ValueError(f"duplicate element name {el.name!r}")
            el.pipeline = self
            self.elements.append(el)
            self.by_name[el.name] = el
        return self

    def add_linked(self, *elements: Element) -> "Pipeline":
        """Add elements and link them in sequence."""
        self.add(*elements)
        for a, b in zip(elements, elements[1:]):
            a.link(b)
        return self

    def get(self, name: str) -> Element:
        return self.by_name[name]

    def verify(self):
        """Static pre-flight of the constructed graph (no buffers run):
        dangling pads, cycles, sync-policy conflicts, tee fan-out without
        queues. Returns a list of ``analysis.Diagnostic`` — empty when
        the graph is clean. See docs/linting.md for the codes."""
        from nnstreamer_tpu.analysis.verify import verify_pipeline

        return verify_pipeline(self)

    def to_dot(self) -> str:
        """Graphviz dot text of the current runtime graph (fused regions
        as clusters) — pipeline/dot.py."""
        from nnstreamer_tpu.pipeline.dot import pipeline_to_dot

        return pipeline_to_dot(self)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """In-process structured metrics read: one dict per element with
        the reference-style windowed stats (same ``InvokeStats`` the
        ``latency``/``throughput`` properties read) plus element-specific
        extras (queue depth/drops, rate drops/duplicates, sink e2e
        percentiles). The HTTP exporter serves the registry-wide view;
        this is the pipeline-scoped one."""
        elements: Dict[str, Any] = {}
        for el in self.elements:
            stats = el._metrics_stats()
            entry: Dict[str, Any] = {
                "type": el.ELEMENT_NAME,
                "latency_us": stats.latency_us,
                "throughput_milli": stats.throughput_milli,
                "invokes": stats.total_invokes,
            }
            entry.update(el.obs_snapshot())
            elements[el.name] = entry
        out = {"pipeline": self.name, "state": self.state.value,
               "elements": elements}
        from nnstreamer_tpu.tensors.pool import get_pool, pool_enabled

        if pool_enabled():
            # the ingest staging pool is process-wide (sources/converters/
            # aggregators share it); surfaced here so one snapshot answers
            # "is the hot path recycling or allocating?"
            out["pool"] = get_pool().snapshot()
        if self._lane_execs:
            # lane executors are spliced, not in self.elements — surface
            # them the way fused regions surface through element stats
            out["lanes"] = {ex.name: ex.obs_snapshot()
                            for ex in self._lane_execs}
        if self._slo_scheduler is not None:
            out["scheduler"] = self._slo_scheduler.snapshot()
        if _memory.ACTIVE is not None:
            out["memory"] = _memory.ACTIVE.snapshot()
        if self._flight is not None:
            # always-on flight recorder (obs/flight.py): streaming
            # stage/e2e quantiles + burn rates, and the continuous
            # variance-attribution report
            out["slo"] = self._flight.slo_snapshot()
            out["attribution"] = self._flight.attribution()
            # raw P² marker states per stage — what fleet federation
            # marker-merges into fleet-level quantiles (obs/distributed)
            out["quantiles"] = self._flight.quantile_states()
        return out

    # -- serving continuity (pipeline/continuity.py) ---------------------------
    def swap_model(self, filter_name: str, model: Optional[str] = None,
                   weights: Any = None) -> Dict[str, Any]:
        """Zero-downtime versioned model swap on a running pipeline:
        drain the owning dispatch window (the cutover fence), install
        the new model/weights under a bumped epoch, invalidate the
        owning fused region exactly once. No frames are dropped and
        output is byte-identical up to the cutover seq."""
        from nnstreamer_tpu.pipeline import continuity as _continuity

        return _continuity.swap_model(self, filter_name, model=model,
                                      weights=weights)

    def checkpoint(self, directory: Optional[str] = None) -> str:
        """Serialize the durable serving state (repo slots, scheduler
        EWMAs/knobs, residency LRU order, flight-recorder quantiles,
        query-server dedup windows) into ``directory`` — defaults to
        ``checkpoint_dir`` / ``NNSTPU_CHECKPOINT``."""
        from nnstreamer_tpu.pipeline import continuity as _continuity

        return _continuity.checkpoint(self, directory)

    def restore(self, directory: Optional[str] = None) -> Dict[str, Any]:
        """Re-arm the warm serving state from a checkpoint written by
        :meth:`checkpoint` (typically in a previous process)."""
        from nnstreamer_tpu.pipeline import continuity as _continuity

        return _continuity.restore(self, directory)

    # -- state ----------------------------------------------------------------
    def start(self) -> "Pipeline":
        """NULL→PLAYING: start all elements (non-sources first so queues and
        filters are ready), then spawn one streaming thread per source."""
        if self.state is State.PLAYING:
            return self
        # frame-ledger tracing (obs/timeline.py): honor NNSTPU_TRACE
        # before any element starts so the source stamp and every
        # instrumentation point see the active timeline. Unset env and
        # no explicit activation = ACTIVE stays None and every trace
        # site is a single is-None test.
        _timeline.maybe_activate_env()
        # fault injection (pipeline/faults.py): same discipline —
        # NNSTPU_FAULTS unset leaves faults.ACTIVE None and every hook
        # is one attribute read on the byte-identical path
        _faults.maybe_activate_env()
        # HBM budget accountant (tensors/memory.py): same kill switch —
        # NNSTPU_HBM_BUDGET unset leaves memory.ACTIVE None and no
        # accounting hook anywhere ever fires
        _memory.maybe_activate_env()
        # persistent compile cache (pipeline/continuity.py): must arm
        # before any backend open() can jit — NNSTPU_COMPILE_CACHE (or
        # an armed checkpoint dir) unset leaves this at two env reads
        from nnstreamer_tpu.pipeline import continuity as _continuity

        _continuity.maybe_enable_compile_cache_env(self)
        sources = [e for e in self.elements if isinstance(e, SourceElement)]
        others = [e for e in self.elements if not isinstance(e, SourceElement)]
        # SLO scheduler before any element starts: admission-point
        # queues bind to it in their start(). The budget check runs
        # before the import so the default (no budget anywhere) path
        # never even loads the serving package.
        if self._slo_scheduler is None and (
                self.slo_budget_ms > 0
                or any(float(el._props.get("slo_budget_ms") or 0.0) > 0
                       for el in self.elements)):
            from nnstreamer_tpu.serving.scheduler import ensure_scheduler

            ensure_scheduler(self)
        # always-on flight recorder (obs/flight.py): installed after the
        # scheduler (so the SLO budget is known) and only when no
        # explicit/env timeline already owns the ledger slot. The
        # recorder rides the existing span sites; NNSTPU_FLIGHT=0 keeps
        # ACTIVE None and the off path exactly as before.
        from nnstreamer_tpu.obs import flight as _flight

        fr = _flight.maybe_install(self)
        if fr is not None:
            self._flight = fr
        for el in others:
            el.start()
        # region fusion after backends opened, before any buffer flows
        # (pipeline/fuse.py); splices persist across restarts
        from nnstreamer_tpu.pipeline.fuse import fuse_pipeline, fusion_enabled

        if self._fuse and fusion_enabled() and self._regions is None:
            self._regions = fuse_pipeline(self)
        for r in self._regions or ():
            r.start()
        # mesh-sharded serving plane (parallel/serve.py): verify the
        # matched-sharding contract across device-passthrough boundaries
        # now that every region/backend holds its plan — a mismatch is a
        # hard MeshShardingError HERE, before any frame could silently
        # reshard; then align the SLO scheduler's admission quantum to
        # the dp fan-out so admitted micro-batches split evenly. Both
        # are no-ops without a mesh= property (or with NNSTPU_MESH=0).
        from nnstreamer_tpu.pipeline.fuse import (
            pipeline_shard_count,
            verify_mesh_boundaries,
        )

        verify_mesh_boundaries(self)
        mesh_quantum = pipeline_shard_count(self)
        if self._slo_scheduler is not None:
            self._slo_scheduler.note_mesh(mesh_quantum)
        if mesh_quantum > 1:
            # mesh-wide batch forming: batch formers (tensor_aggregator
            # — the element the query server pipeline batches through)
            # round their window up to the dp fan-out so formed batches
            # split evenly across the mesh
            for el in self.elements:
                hook = getattr(el, "note_mesh_quantum", None)
                if hook is not None:
                    hook(mesh_quantum)
        # ingest lane splicing after fusion (pipeline/lanes.py): a
        # transform folded into a region is already out of the replicable
        # segment, so its math runs device-side while lanes parallelize
        # what host work remains; splices persist across restarts
        from nnstreamer_tpu.pipeline.lanes import effective_lanes, splice_lanes

        if self._lane_execs is None:
            self._lane_execs = splice_lanes(self, effective_lanes(self.lanes))
        for ex in self._lane_execs:
            ex.start()
        # serving-continuity restore (pipeline/continuity.py): after the
        # scheduler / flight recorder / residency units exist, before the
        # first frame flows — so the warm state is in place for frame 0
        _continuity.maybe_restore_env(self)
        for el in sources:
            el.start()
        self.state = State.PLAYING
        # GST_DEBUG_DUMP_DOT_DIR equivalent (pipeline/dot.py) — after
        # fusion so the dump shows the regions that will actually run
        from nnstreamer_tpu.pipeline.dot import maybe_dump_dot

        maybe_dump_dot(self)
        self._eos_pending = len(sources)
        for src in sources:
            t = threading.Thread(
                target=src.run_loop, args=(self,),
                name=f"{self.name}:{src.name}", daemon=True
            )
            self._threads.append(t)
            t.start()
        # liveness watchdog (pipeline/supervise.py): armed only with an
        # explicit deadline (Pipeline(watchdog_s=) / NNSTPU_WATCHDOG_S)
        # — default off, zero extra threads
        wd_s = self._effective_watchdog_s()
        if wd_s > 0 and self._watchdog is None:
            from nnstreamer_tpu.pipeline.supervise import PipelineWatchdog

            self._watchdog = PipelineWatchdog(self, wd_s)
            self._watchdog.start()
        return self

    def _effective_watchdog_s(self) -> float:
        if self.watchdog_s > 0:
            return self.watchdog_s
        import os

        raw = os.environ.get("NNSTPU_WATCHDOG_S", "").strip()
        if not raw:
            return 0.0
        try:
            return float(raw)
        except ValueError:
            log.warning("NNSTPU_WATCHDOG_S=%r is not a number; watchdog "
                        "stays off", raw)
            return 0.0

    def stop(self) -> "Pipeline":
        if self.state is State.NULL:
            return self
        # watchdog first: teardown quiescence must not read as a stall
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        for el in self.elements:
            if isinstance(el, SourceElement):
                el.stop()
        for t in self._threads:
            t.join(timeout=10)
        self._threads.clear()
        # lane executors stop after the source threads (their upstream)
        # are parked and before the elements they feed shut down
        for ex in self._lane_execs or ():
            ex.stop()
        for el in self.elements:
            if not isinstance(el, SourceElement):
                el.stop()
        for r in self._regions or ():
            r.stop()
        # drop every staging arena's free slabs (shared ingest pool +
        # per-lane pools): a stopped pipeline must not pin peak-rate
        # slab bytes for the life of the process (nns_pool_bytes_held
        # returns to the outstanding working set)
        from nnstreamer_tpu.tensors.pool import release_all_pools

        release_all_pools()
        self.state = State.NULL
        # serving-continuity checkpoint (pipeline/continuity.py): every
        # element is stopped and every dispatch window drained, so the
        # serialized state is consistent. Unarmed ⇒ one env read.
        from nnstreamer_tpu.pipeline import continuity as _continuity

        _continuity.maybe_checkpoint_on_stop(self)
        # retire the flight recorder before the env-owned export check:
        # a pending tail dump near EOS flushes here, and the recorder
        # object stays on self._flight for the post-EOS footer / bench
        if self._flight is not None:
            from nnstreamer_tpu.obs import flight as _flight

            _flight.retire(self._flight)
        # an env-owned timeline (NNSTPU_TRACE=<path>) exports its ledger
        # once the run is over; explicitly installed timelines are the
        # caller's to export
        _timeline.maybe_export_env()
        return self

    # -- bus ------------------------------------------------------------------
    def post_message(self, msg: Message) -> None:
        self._bus.put(msg)

    def post_error(self, source: Element, error: Exception) -> None:
        log.error("pipeline %s: error from %s: %s", self.name,
                  source.name if source else "?", error)
        self._bus.put(Message("error", source, error))

    def post_warning(self, source: Optional[Element], text: str) -> None:
        """Non-fatal bus message: logged, delivered to ``pop_message``
        readers, and skipped over by ``wait()`` (the pipeline keeps
        running — the reference's GST_MESSAGE_WARNING semantics)."""
        log.warning("pipeline %s: warning from %s: %s", self.name,
                    source.name if source else "?", text)
        self._bus.put(Message("warning", source, text=text))

    def pop_message(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            return self._bus.get(timeout=timeout)
        except _queue.Empty:
            return None

    def wait(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Block until every source reached EOS (returns the final EOS
        message) or any element errored (returns the error message)."""
        remaining = self._eos_pending
        deadline = None if timeout is None else (
            threading.TIMEOUT_MAX if timeout < 0 else timeout
        )
        import time

        t_end = None if deadline is None else time.monotonic() + deadline
        while True:
            t_left = None if t_end is None else max(0.0, t_end - time.monotonic())
            msg = self.pop_message(timeout=t_left)
            if msg is None:
                return None  # timed out
            if msg.kind == "error":
                return msg
            if msg.kind == "eos":
                remaining -= 1
                if remaining <= 0:
                    return msg

    def run(self, timeout: Optional[float] = None) -> Optional[Message]:
        """start() + wait() + stop(); raises on error message."""
        self.start()
        try:
            msg = self.wait(timeout=timeout)
            if msg is not None and msg.kind == "error":
                raise FlowError(str(msg.error)) from msg.error
            return msg
        finally:
            self.stop()
