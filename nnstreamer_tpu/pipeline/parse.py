"""gst-launch-style pipeline-description parser.

The reference's CLI *is* ``gst-launch-1.0`` with pipeline descriptions
(``Documentation/gst-launch-script-example.md``); the same grammar is used
programmatically via ``gst_parse_launch``. We implement the useful core of
that grammar over our element registry so reference pipelines translate
almost verbatim::

    parse_launch(
      "videotestsrc num-buffers=30 ! tensor_converter ! "
      "tensor_filter framework=jax model=m.msgpack ! "
      "tensor_decoder mode=image_labeling option1=labels.txt ! "
      "tensor_sink name=out"
    )

Supported grammar (tools/development/parser is the reference's bison
grammar for the same language):

- ``element prop=value ...``  — properties; values may be quoted.
- ``a ! b ! c``               — linking.
- ``name=foo`` then ``foo.``  — named-element branch points (tee/demux):
  ``t. ! queue ! sink`` continues from element ``foo``'s next free src pad.
- ``foo.src_1`` / ``foo.sink_0`` — named-PAD references select an exact
  pad; request pads (src_N/sink_N) are created in order on demand.
- caps filter strings (``other/tensors,num_tensors=1,...``) between ``!``
  become :class:`CapsFilter` elements.

Parsing is split into two layers so the same grammar serves two
consumers (the reference keeps the same split: the bison grammar builds
a ``graph_t`` which ``gst_parse_launch`` then instantiates):

- :func:`parse_description` — pure syntax: tokenize (tracking source
  columns) and build chains of :class:`LaunchNode`. No registry access,
  no element construction — this is what the static verifier
  (``nnstreamer_tpu.analysis``) consumes to check a pipeline without
  creating any runtime state.
- :func:`parse_launch` — instantiate the description against the element
  registry and resolve links into a live :class:`Pipeline`.

Errors raise :class:`ParseError` (a ``ValueError``) carrying the source
column (``pos``, 0-based) and token index, so linter diagnostics and
runtime parse errors cite the same location.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList
from nnstreamer_tpu.pipeline.element import Element, Pad
from nnstreamer_tpu.pipeline.pipeline import Pipeline
from nnstreamer_tpu.registry import ELEMENT, get_subplugin, subplugin


class ParseError(ValueError):
    """Pipeline-description error with a source position.

    ``pos`` is the 0-based column of the offending token in the
    description string (None when unknown); ``token_index`` its index in
    the token stream. The rendered message carries the 1-based column so
    CLI output and analyzer diagnostics cite the same location.
    """

    def __init__(self, message: str, pos: Optional[int] = None,
                 token_index: Optional[int] = None):
        if pos is not None:
            message = f"{message} (at column {pos + 1})"
        super().__init__(message)
        self.pos = pos
        self.token_index = token_index


class PropertyParseError(ParseError, KeyError):
    """Unknown-property error: positional like every ParseError, but still
    a ``KeyError`` because that is ``Element.set_property``'s contract
    (callers distinguish bad-property from bad-structure by type)."""


@dataclasses.dataclass(frozen=True)
class Token:
    """One lexed token: text (quotes/escapes resolved) + source column."""

    text: str
    pos: int     # 0-based column of the token's first character
    index: int   # position in the token stream


@dataclasses.dataclass
class LaunchNode:
    """One node of a parsed (but not instantiated) description chain."""

    kind: str                      # "element" | "ref" | "refpad" | "caps"
    factory: Optional[str] = None  # element factory name ("caps": capsfilter)
    props: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)  # (key, value, source column)
    ref: Optional[str] = None      # referenced element name (ref/refpad)
    pad: Optional[str] = None      # referenced pad name (refpad)
    caps: Optional[str] = None     # raw caps string (kind == "caps")
    pos: int = 0                   # source column of the node's first token

    @property
    def name(self) -> Optional[str]:
        """The explicit ``name=`` property, if one was given."""
        for k, v, _ in self.props:
            if k == "name":
                return v
        return None


def tokenize_launch(description: str) -> List[Token]:
    """Lex a description into position-carrying tokens.

    Same token stream a posix shlex with ``punctuation_chars='!'`` would
    produce (whitespace-split words, quotes stripped, backslash escapes,
    ``!`` always its own token) — but every token remembers the column it
    started at, which is what gives parse errors and static-analyzer
    diagnostics a precise location.
    """
    tokens: List[Token] = []
    i, n = 0, len(description)
    while i < n:
        ch = description[i]
        if ch.isspace():
            i += 1
            continue
        if ch == "!":
            tokens.append(Token("!", i, len(tokens)))
            i += 1
            continue
        start = i
        parts: List[str] = []
        while i < n and not description[i].isspace() and description[i] != "!":
            c = description[i]
            if c in ('"', "'"):
                end = description.find(c, i + 1)
                if end < 0:
                    raise ParseError(f"unterminated {c} quote", pos=i,
                                     token_index=len(tokens))
                parts.append(description[i + 1:end])
                i = end + 1
            elif c == "\\" and i + 1 < n:
                parts.append(description[i + 1])
                i += 2
            else:
                parts.append(c)
                i += 1
        tokens.append(Token("".join(parts), start, len(tokens)))
    return tokens


@subplugin(ELEMENT, "capsfilter")
class CapsFilter(Element):
    """Constrains stream caps (gst capsfilter): intersects incoming caps with
    its ``caps`` property and forwards; buffers pass through untouched."""

    ELEMENT_NAME = "capsfilter"
    PROPERTIES = {**Element.PROPERTIES, "caps": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        want = self.get_property("caps")
        if want is None:
            return caps
        merged = caps.intersect(want)
        if merged is None:
            raise ValueError(
                f"{self.name}: caps {caps!r} do not satisfy filter {want!r}"
            )
        return merged.fixate()


def _split_caps_fields(text: str) -> List[str]:
    """Split a caps string on commas, respecting double-quoted values so
    multi-tensor fields like dimensions="3:224:224:1,3:300:300:1" stay
    whole."""
    parts, cur, in_q = [], [], False
    for ch in text:
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
        elif ch == "," and not in_q:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def parse_caps_string(text: str) -> Caps:
    """Parse ``media/type,k=v,k2=v2`` into Caps (values kept as str/int).

    Multi-tensor values may contain commas, quoted or bare: a comma-part
    with no ``=`` continues the previous field's value, so both
    ``dimensions="2:2,3:3"`` and ``dimensions=2:2,3:3`` parse (the launch
    lexer strips quotes before this function sees the string)."""
    raw_parts = _split_caps_fields(text)
    # merge '='-less parts into the previous field's value
    parts: List[str] = [raw_parts[0]]
    for item in raw_parts[1:]:
        if "=" in item or len(parts) == 1:
            parts.append(item)
        else:
            parts[-1] += "," + item
    name = parts[0].strip()
    fields = {}
    for item in parts[1:]:
        if not item.strip():
            continue
        if "=" not in item:
            raise ValueError(f"bad caps field {item!r} in {text!r}")
        k, v = item.split("=", 1)
        v = v.strip().strip('"')
        # strip gst type annotations like (int)640 / (string)RGB
        if v.startswith("(") and ")" in v:
            v = v[v.index(")") + 1:]
        try:
            v2: object = int(v)
        except ValueError:
            v2 = v
        fields[k.strip()] = v2
    return Caps(name, fields)


def _is_caps_token(tok: str) -> bool:
    head = tok.split(",", 1)[0]
    return "/" in head and "=" not in head


def parse_description(description: str) -> List[List[LaunchNode]]:
    """Pure-syntax pass: description → chains of :class:`LaunchNode`.

    No registry lookups and no element construction happen here — factory
    names, properties, and references are recorded verbatim with their
    source columns. ``parse_launch`` instantiates the result; the static
    analyzer verifies it without instantiating anything.
    """
    tokens = tokenize_launch(description)
    chains: List[List[LaunchNode]] = [[]]
    current: Optional[LaunchNode] = None
    linked = False  # was the previous token a "!"?

    def close():
        nonlocal current
        if current is not None:
            chains[-1].append(current)
            current = None

    for tok in tokens:
        t = tok.text
        if t == "!":
            close()
            linked = True
            continue
        if "=" in t and current is not None and not _is_caps_token(t):
            k, v = t.split("=", 1)
            current.props.append((k, v, tok.pos))
            continue
        # a new node begins; if no "!" came before it, start a new chain
        close()
        if not linked and chains[-1]:
            chains.append([])
        linked = False
        if t.endswith(".") and len(t) > 1 and "=" not in t:
            chains[-1].append(LaunchNode("ref", ref=t[:-1], pos=tok.pos))
        elif ("." in t and "=" not in t and not _is_caps_token(t)
                and not t.startswith(".")):
            # gst-launch named-pad reference: ``name.pad`` selects that
            # exact pad (``s.src_1 ! ...`` / ``... ! m.sink_0``)
            name, pad = t.split(".", 1)
            chains[-1].append(LaunchNode("refpad", ref=name, pad=pad,
                                         pos=tok.pos))
        elif _is_caps_token(t):
            current = LaunchNode("caps", factory="capsfilter", caps=t,
                                 pos=tok.pos)
        else:
            current = LaunchNode("element", factory=t, pos=tok.pos)
    close()
    return chains


def _make_element(factory_name: str, pos: Optional[int] = None) -> Element:
    from nnstreamer_tpu.config import get_conf

    conf = get_conf()
    # element-restriction allowlist (reference meson.build:531-540:
    # [element-restriction] enable_element_restriction + allowed_elements;
    # the short `enable`/`restricted_elements` spellings are also accepted)
    allowed = conf.allowed_elements()
    if allowed is not None and factory_name not in allowed:
        # fail closed at parse: a restricted deployment never instantiates
        # an unlisted element (reference enable-element-restriction)
        raise ParseError(
            f"element {factory_name!r} is not in the configured "
            f"element-restriction allowlist", pos=pos)
    factory = get_subplugin(ELEMENT, factory_name)
    if factory is None:
        raise ParseError(f"no such element factory {factory_name!r}",
                         pos=pos)
    return factory()


def _build_element(node: LaunchNode) -> Element:
    """Instantiate one LaunchNode and apply its properties."""
    if node.kind == "caps":
        el: Element = CapsFilter()
        el.set_property("caps", parse_caps_string(node.caps))
    else:
        if "=" in (node.factory or ""):
            raise ParseError(
                f"property token {node.factory!r} has no element to "
                f"apply to", pos=node.pos)
        el = _make_element(node.factory, pos=node.pos)
    for k, v, pos in node.props:
        try:
            if k == "name":
                el.name = v  # set before Pipeline.add registers it
            elif k == "caps" and isinstance(el, CapsFilter):
                el.set_property("caps", parse_caps_string(v))
            else:
                el.set_property(k, v)
        except KeyError as e:
            # carry the property token's position, preserving KeyError-ness
            raise PropertyParseError(e.args[0] if e.args else str(e),
                                     pos=pos) from e
    return el


def parse_launch(description: str, pipeline: Optional[Pipeline] = None,
                 lanes: Optional[int] = None,
                 slo_budget_ms: Optional[float] = None,
                 error_policy: Optional[str] = None,
                 watchdog_s: Optional[float] = None) -> Pipeline:
    """Build a Pipeline from a gst-launch-style description.

    Two-pass like gst_parse_launch: first build all elements and record the
    link structure (so ``... ! mux.`` may reference an element defined later
    in the description), then resolve links.

    ``lanes`` sets the pipeline's ingest lane count (``pipeline/lanes.py``);
    None leaves the pipeline's configured value (serial by default).
    ``slo_budget_ms`` sets the pipeline-wide SLO budget
    (``serving/scheduler.py``): deadline admission, EDF ordering and
    feedback-tuned batch forming on the admission-point queues; None/0
    leaves the scheduler off entirely (byte-identical FIFO path).
    ``error_policy`` sets the pipeline-default recovery policy
    (``pipeline/supervise.py``: halt | skip-frame | retry | degrade;
    elements override via their ``error-policy`` property) and
    ``watchdog_s`` arms the stall watchdog with that deadline; None
    leaves both at the fail-fast defaults.
    """
    pipe = pipeline or Pipeline()
    if lanes is not None:
        pipe.lanes = max(1, int(lanes))
    if slo_budget_ms is not None:
        pipe.slo_budget_ms = max(0.0, float(slo_budget_ms))
    if error_policy is not None:
        pipe.error_policy = error_policy
    if watchdog_s is not None:
        pipe.watchdog_s = max(0.0, float(watchdog_s))

    # -- pass 1: nodes & chains (syntax via parse_description) ---------------
    # node: ("el", Element) | ("ref", name) | ("refpad", name, pad)
    chains: List[List[tuple]] = []
    for ast_chain in parse_description(description):
        chain: List[tuple] = []
        for node in ast_chain:
            if node.kind == "ref":
                chain.append(("ref", node.ref, node.pos))
            elif node.kind == "refpad":
                chain.append(("refpad", node.ref, node.pos, node.pad))
            else:
                el = _build_element(node)
                pipe.add(el)
                chain.append(("el", el, node.pos))
        chains.append(chain)

    # -- pass 2: resolve links ----------------------------------------------
    def resolve(node) -> Element:
        kind, val, pos = node[0], node[1], node[2]
        if kind == "el":
            return val
        if val not in pipe.by_name:
            raise ParseError(f"unknown element reference {val!r}", pos=pos)
        return pipe.by_name[val]

    implied_sinks: List = []

    def named_pad(el: Element, pname: str, direction: str, pos: int):
        pads = el.srcpads if direction == "src" else el.sinkpads
        for p in pads:
            if p.name == pname:
                return p
        m = None
        if pname.startswith(f"{direction}_"):
            suffix = pname[len(direction) + 1:]
            m = int(suffix) if suffix.isdigit() else None
        if m is None:
            raise ParseError(
                f"element {el.name!r} has no {direction} pad {pname!r} "
                f"(has: {[p.name for p in pads]})", pos=pos)
        # request-pad convention (src_N/sink_N): pads are POSITIONAL in
        # the elements that use them (split segment i → i-th pad, mux
        # pad index → tensor slot), so create every index up to the one
        # requested — a description may reference them in any order.
        # Implied-but-unlinked SINK pads are validated after all links
        # resolve (an input a sync policy would wait on forever must be
        # a parse error, not a hang); unlinked src pads just drop.
        try:
            while len(pads) <= m:
                if direction == "sink":
                    implied_sinks.append(el.request_sink_pad())
                else:
                    el.request_src_pad()
        except NotImplementedError as e:
            raise ParseError(
                f"element {el.name!r} has no {direction} pad {pname!r} "
                f"and cannot grow one ({e})", pos=pos) from e
        return pads[m]

    for chain in chains:
        for a, b in zip(chain, chain[1:]):
            ea, eb = resolve(a), resolve(b)
            a_pad = a[3] if a[0] == "refpad" else None
            b_pad = b[3] if b[0] == "refpad" else None
            if a_pad is None and b_pad is None:
                ea.link(eb)
                continue
            if a_pad is not None:
                src = named_pad(ea, a_pad, "src", a[2])
            else:
                src = next((p for p in ea.srcpads if p.peer is None), None)
                if src is None:
                    try:
                        # tee/split/demux grow src pads on demand
                        src = ea.request_src_pad()
                    except NotImplementedError:
                        raise ParseError(
                            f"{ea.name}: no free src pad",
                            pos=a[2]) from None
            if b_pad is not None:
                sink = named_pad(eb, b_pad, "sink", b[2])
            else:
                sink = next((p for p in eb.sinkpads if p.peer is None),
                            None)
                if sink is None:
                    sink = eb.request_sink_pad()
            src.link(sink)
    for pad in implied_sinks:
        if pad.peer is None:
            raise ParseError(
                f"sink pad {pad.element.name}.{pad.name} was implied by a "
                f"higher-numbered reference but never linked — a sync "
                f"policy would wait on it forever")
    return pipe
