"""gst-launch-style pipeline-description parser.

The reference's CLI *is* ``gst-launch-1.0`` with pipeline descriptions
(``Documentation/gst-launch-script-example.md``); the same grammar is used
programmatically via ``gst_parse_launch``. We implement the useful core of
that grammar over our element registry so reference pipelines translate
almost verbatim::

    parse_launch(
      "videotestsrc num-buffers=30 ! tensor_converter ! "
      "tensor_filter framework=jax model=m.msgpack ! "
      "tensor_decoder mode=image_labeling option1=labels.txt ! "
      "tensor_sink name=out"
    )

Supported grammar (tools/development/parser is the reference's bison
grammar for the same language):

- ``element prop=value ...``  — properties; values may be quoted.
- ``a ! b ! c``               — linking.
- ``name=foo`` then ``foo.``  — named-element branch points (tee/demux):
  ``t. ! queue ! sink`` continues from element ``foo``'s next free src pad.
- ``foo.src_1`` / ``foo.sink_0`` — named-PAD references select an exact
  pad; request pads (src_N/sink_N) are created in order on demand.
- caps filter strings (``other/tensors,num_tensors=1,...``) between ``!``
  become :class:`CapsFilter` elements.
"""

from __future__ import annotations

import shlex
from typing import List, Optional, Tuple

from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList
from nnstreamer_tpu.pipeline.element import Element, Pad
from nnstreamer_tpu.pipeline.pipeline import Pipeline
from nnstreamer_tpu.registry import ELEMENT, get_subplugin, subplugin


@subplugin(ELEMENT, "capsfilter")
class CapsFilter(Element):
    """Constrains stream caps (gst capsfilter): intersects incoming caps with
    its ``caps`` property and forwards; buffers pass through untouched."""

    ELEMENT_NAME = "capsfilter"
    PROPERTIES = {**Element.PROPERTIES, "caps": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        want = self.get_property("caps")
        if want is None:
            return caps
        merged = caps.intersect(want)
        if merged is None:
            raise ValueError(
                f"{self.name}: caps {caps!r} do not satisfy filter {want!r}"
            )
        return merged.fixate()


def _split_caps_fields(text: str) -> List[str]:
    """Split a caps string on commas, respecting double-quoted values so
    multi-tensor fields like dimensions="3:224:224:1,3:300:300:1" stay
    whole."""
    parts, cur, in_q = [], [], False
    for ch in text:
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
        elif ch == "," and not in_q:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def parse_caps_string(text: str) -> Caps:
    """Parse ``media/type,k=v,k2=v2`` into Caps (values kept as str/int).

    Multi-tensor values may contain commas, quoted or bare: a comma-part
    with no ``=`` continues the previous field's value, so both
    ``dimensions="2:2,3:3"`` and ``dimensions=2:2,3:3`` parse (the launch
    lexer strips quotes before this function sees the string)."""
    raw_parts = _split_caps_fields(text)
    # merge '='-less parts into the previous field's value
    parts: List[str] = [raw_parts[0]]
    for item in raw_parts[1:]:
        if "=" in item or len(parts) == 1:
            parts.append(item)
        else:
            parts[-1] += "," + item
    name = parts[0].strip()
    fields = {}
    for item in parts[1:]:
        if not item.strip():
            continue
        if "=" not in item:
            raise ValueError(f"bad caps field {item!r} in {text!r}")
        k, v = item.split("=", 1)
        v = v.strip().strip('"')
        # strip gst type annotations like (int)640 / (string)RGB
        if v.startswith("(") and ")" in v:
            v = v[v.index(")") + 1:]
        try:
            v2: object = int(v)
        except ValueError:
            v2 = v
        fields[k.strip()] = v2
    return Caps(name, fields)


def _is_caps_token(tok: str) -> bool:
    head = tok.split(",", 1)[0]
    return "/" in head and "=" not in head


def _make_element(factory_name: str, props: List[Tuple[str, str]]) -> Element:
    from nnstreamer_tpu.config import get_conf

    conf = get_conf()
    # element-restriction allowlist (reference meson.build:531-540:
    # [element-restriction] enable_element_restriction + allowed_elements;
    # the short `enable`/`restricted_elements` spellings are also accepted)
    allowed = conf.allowed_elements()
    if allowed is not None and factory_name not in allowed:
        # fail closed at parse: a restricted deployment never instantiates
        # an unlisted element (reference enable-element-restriction)
        raise ValueError(
            f"element {factory_name!r} is not in the configured "
            f"element-restriction allowlist")
    factory = get_subplugin(ELEMENT, factory_name)
    if factory is None:
        raise ValueError(f"no such element factory {factory_name!r}")
    el: Element = factory()
    for k, v in props:
        if k == "name":
            el.name = v
        elif k == "caps" and isinstance(el, CapsFilter):
            el.set_property("caps", parse_caps_string(v))
        else:
            el.set_property(k, v)
    return el


def parse_launch(description: str, pipeline: Optional[Pipeline] = None
                 ) -> Pipeline:
    """Build a Pipeline from a gst-launch-style description.

    Two-pass like gst_parse_launch: first build all elements and record the
    link structure (so ``... ! mux.`` may reference an element defined later
    in the description), then resolve links.
    """
    pipe = pipeline or Pipeline()
    lexer = shlex.shlex(description, posix=True, punctuation_chars="!")
    lexer.whitespace_split = True
    tokens = list(lexer)

    # -- pass 1: nodes & chains ---------------------------------------------
    # node: ("el", Element) | ("ref", name)
    chains: List[List[tuple]] = [[]]
    current: Optional[Element] = None
    linked = False  # was the previous token a "!"?

    def close_element():
        nonlocal current
        if current is not None:
            pipe.add(current)
            chains[-1].append(("el", current))
            current = None

    for tok in tokens:
        if tok == "!":
            close_element()
            linked = True
            continue
        if "=" in tok and current is not None and not _is_caps_token(tok):
            k, v = tok.split("=", 1)
            if k == "name":
                current.name = v  # set before close_element registers it
            elif k == "caps" and isinstance(current, CapsFilter):
                current.set_property("caps", parse_caps_string(v))
            else:
                current.set_property(k, v)
            continue
        # a new node begins; if no "!" came before it, start a new chain
        close_element()
        if not linked and chains[-1]:
            chains.append([])
        linked = False
        if tok.endswith(".") and len(tok) > 1 and "=" not in tok:
            chains[-1].append(("ref", tok[:-1]))
        elif ("." in tok and "=" not in tok and not _is_caps_token(tok)
                and not tok.startswith(".")):
            # gst-launch named-pad reference: ``name.pad`` selects that
            # exact pad (``s.src_1 ! ...`` / ``... ! m.sink_0``)
            name, pad = tok.split(".", 1)
            chains[-1].append(("refpad", name, pad))
        elif _is_caps_token(tok):
            current = CapsFilter()
            current.set_property("caps", parse_caps_string(tok))
        else:
            current = _make_element(tok, [])
    close_element()

    # -- pass 2: resolve links ----------------------------------------------
    def resolve(node) -> Element:
        kind, val = node[0], node[1]
        if kind == "el":
            return val
        if val not in pipe.by_name:
            raise ValueError(f"unknown element reference {val!r}")
        return pipe.by_name[val]

    implied_sinks: List = []

    def named_pad(el: Element, pname: str, direction: str):
        pads = el.srcpads if direction == "src" else el.sinkpads
        for p in pads:
            if p.name == pname:
                return p
        m = None
        if pname.startswith(f"{direction}_"):
            suffix = pname[len(direction) + 1:]
            m = int(suffix) if suffix.isdigit() else None
        if m is None:
            raise ValueError(
                f"element {el.name!r} has no {direction} pad {pname!r} "
                f"(has: {[p.name for p in pads]})")
        # request-pad convention (src_N/sink_N): pads are POSITIONAL in
        # the elements that use them (split segment i → i-th pad, mux
        # pad index → tensor slot), so create every index up to the one
        # requested — a description may reference them in any order.
        # Implied-but-unlinked SINK pads are validated after all links
        # resolve (an input a sync policy would wait on forever must be
        # a parse error, not a hang); unlinked src pads just drop.
        try:
            while len(pads) <= m:
                if direction == "sink":
                    implied_sinks.append(el.request_sink_pad())
                else:
                    el.request_src_pad()
        except NotImplementedError as e:
            raise ValueError(
                f"element {el.name!r} has no {direction} pad {pname!r} "
                f"and cannot grow one ({e})") from e
        return pads[m]

    for chain in chains:
        for a, b in zip(chain, chain[1:]):
            ea, eb = resolve(a), resolve(b)
            a_pad = a[2] if a[0] == "refpad" else None
            b_pad = b[2] if b[0] == "refpad" else None
            if a_pad is None and b_pad is None:
                ea.link(eb)
                continue
            if a_pad is not None:
                src = named_pad(ea, a_pad, "src")
            else:
                src = next((p for p in ea.srcpads if p.peer is None), None)
                if src is None:
                    try:
                        # tee/split/demux grow src pads on demand
                        src = ea.request_src_pad()
                    except NotImplementedError:
                        raise ValueError(
                            f"{ea.name}: no free src pad") from None
            if b_pad is not None:
                sink = named_pad(eb, b_pad, "sink")
            else:
                sink = next((p for p in eb.sinkpads if p.peer is None),
                            None)
                if sink is None:
                    sink = eb.request_sink_pad()
            src.link(sink)
    for pad in implied_sinks:
        if pad.peer is None:
            raise ValueError(
                f"sink pad {pad.element.name}.{pad.name} was implied by a "
                f"higher-numbered reference but never linked — a sync "
                f"policy would wait on it forever")
    return pipe
