"""gst-launch-style pipeline-description parser.

The reference's CLI *is* ``gst-launch-1.0`` with pipeline descriptions
(``Documentation/gst-launch-script-example.md``); the same grammar is used
programmatically via ``gst_parse_launch``. We implement the useful core of
that grammar over our element registry so reference pipelines translate
almost verbatim::

    parse_launch(
      "videotestsrc num-buffers=30 ! tensor_converter ! "
      "tensor_filter framework=jax model=m.msgpack ! "
      "tensor_decoder mode=image_labeling option1=labels.txt ! "
      "tensor_sink name=out"
    )

Supported grammar (tools/development/parser is the reference's bison
grammar for the same language):

- ``element prop=value ...``  — properties; values may be quoted.
- ``a ! b ! c``               — linking.
- ``name=foo`` then ``foo.``  — named-element branch points (tee/demux):
  ``t. ! queue ! sink`` continues from element ``foo``'s next free src pad.
- caps filter strings (``other/tensors,num_tensors=1,...``) between ``!``
  become :class:`CapsFilter` elements.
"""

from __future__ import annotations

import shlex
from typing import List, Optional, Tuple

from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList
from nnstreamer_tpu.pipeline.element import Element, Pad
from nnstreamer_tpu.pipeline.pipeline import Pipeline
from nnstreamer_tpu.registry import ELEMENT, get_subplugin, subplugin


@subplugin(ELEMENT, "capsfilter")
class CapsFilter(Element):
    """Constrains stream caps (gst capsfilter): intersects incoming caps with
    its ``caps`` property and forwards; buffers pass through untouched."""

    ELEMENT_NAME = "capsfilter"
    PROPERTIES = {**Element.PROPERTIES, "caps": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        want = self.get_property("caps")
        if want is None:
            return caps
        merged = caps.intersect(want)
        if merged is None:
            raise ValueError(
                f"{self.name}: caps {caps!r} do not satisfy filter {want!r}"
            )
        return merged.fixate()


def parse_caps_string(text: str) -> Caps:
    """Parse ``media/type,k=v,k2=v2`` into Caps (values kept as str/int)."""
    parts = text.split(",")
    name = parts[0].strip()
    fields = {}
    for item in parts[1:]:
        if not item.strip():
            continue
        if "=" not in item:
            raise ValueError(f"bad caps field {item!r} in {text!r}")
        k, v = item.split("=", 1)
        v = v.strip().strip('"')
        # strip gst type annotations like (int)640 / (string)RGB
        if v.startswith("(") and ")" in v:
            v = v[v.index(")") + 1:]
        try:
            v2: object = int(v)
        except ValueError:
            v2 = v
        fields[k.strip()] = v2
    return Caps(name, fields)


def _is_caps_token(tok: str) -> bool:
    head = tok.split(",", 1)[0]
    return "/" in head and "=" not in head


def _make_element(factory_name: str, props: List[Tuple[str, str]]) -> Element:
    factory = get_subplugin(ELEMENT, factory_name)
    if factory is None:
        raise ValueError(f"no such element factory {factory_name!r}")
    el: Element = factory()
    for k, v in props:
        if k == "name":
            el.name = v
        elif k == "caps" and isinstance(el, CapsFilter):
            el.set_property("caps", parse_caps_string(v))
        else:
            el.set_property(k, v)
    return el


def parse_launch(description: str, pipeline: Optional[Pipeline] = None
                 ) -> Pipeline:
    """Build a Pipeline from a gst-launch-style description."""
    pipe = pipeline or Pipeline()
    lexer = shlex.shlex(description, posix=True, punctuation_chars="!")
    lexer.whitespace_split = True
    tokens = list(lexer)

    prev: Optional[Element] = None  # element whose src feeds the next link
    pending_props: List[Tuple[str, str]] = []
    current: Optional[Element] = None
    link_pending = False

    def finish_current():
        nonlocal current, prev, link_pending
        if current is None:
            return
        pipe.add(current)
        if link_pending and prev is not None:
            prev.link(current)
        prev = current
        link_pending = False
        current = None

    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok == "!":
            finish_current()
            link_pending = True
        elif "=" in tok and current is not None and not _is_caps_token(tok):
            k, v = tok.split("=", 1)
            if k == "name":
                current.name = v
            elif k == "caps" and isinstance(current, CapsFilter):
                current.set_property("caps", parse_caps_string(v))
            else:
                current.set_property(k, v)
        elif tok.endswith(".") and len(tok) > 1:
            # branch point: continue from a named element
            finish_current()
            ref = tok[:-1]
            if ref not in pipe.by_name:
                raise ValueError(f"unknown element reference {ref!r}")
            prev = pipe.by_name[ref]
            link_pending = False
        elif _is_caps_token(tok):
            finish_current()
            cf = CapsFilter()
            cf.set_property("caps", parse_caps_string(tok))
            current = cf
        else:
            finish_current()
            current = _make_element(tok, [])
        i += 1
    finish_current()
    return pipe
