"""DispatchWindow — bounded async dispatch per filter / fused region.

XLA dispatch is asynchronous: ``jitted(...)`` returns device handles
before the device finishes. The pipeline previously consumed that
asynchrony one frame at a time — the next frame's host work only started
once the previous frame's downstream chain returned, and any downstream
materialization point fenced every frame individually, so the device sat
idle between dispatches (BENCH_r05: flagship at 13.4% of the device
ceiling). The overlap layer's contract instead allows up to ``inflight=K``
device batches outstanding per dispatching element: host work for frame
N+1 proceeds while the device computes frame N, and the producer thread
only blocks (fences the OLDEST outstanding batch) when the window is full
— bounded pipelining, same ordering.

The window also owns the staging-buffer recycle point: a pooled host
array consumed by an H2D transfer (``tensors/pool.py``, carried in
``meta["pool_stash"]``) must not be rewritten while the transfer or the
dispatch reading it is in flight. Fencing entry N proves dispatch N
completed, so its stash is released exactly there. Batched window
uploads (``tensors/buffer.py`` ``upload_many``) extend the same
contract: the single window slab that staged a whole drained run rides
the run's LAST buffer's stash, so the in-order fence releases it only
after every dispatch that read any slot of that upload has completed
(a slot still adopted as a DeviceBuffer host view keeps the slab out
of circulation through the pool's refcount guard regardless).

Instrumented as ``nns_filter_inflight`` (current window occupancy) and
``nns_filter_fence_wait_seconds`` (time spent blocked in each fence —
near-zero means the device finishes before the window fills; large means
the pipeline is device-bound at this element).
"""

from __future__ import annotations

import collections
import time
import weakref
from typing import Any, Deque, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.tensors.buffer import H2D_EXCLUSIVE_META, is_device_array

log = get_logger("dispatch")

#: meta key carrying pool-owned host staging arrays whose release is
#: deferred to the fence point (set by Queue prefetch-device; a batched
#: window upload additionally parks its shared window slab on the run's
#: last buffer here)
POOL_STASH_META = "pool_stash"


def release_shed_payload(buf) -> None:
    """Release a shed/revoked frame's device payload and pool pins NOW.

    A frame the EDF scheduler sheds (or that admission revokes) never
    reaches a fence, so nothing would release its staged pool slabs or
    drop its freshly-uploaded device tensors until GC happens to find
    the dead wrapper — shed work silently pinning HBM and slab bytes is
    exactly the failure mode the memory budget exists to prevent. Safe
    on any buffer: pops the fence-deferred ``pool_stash`` back to the
    pool, and clears the device tensor list only when the payload is
    marked ``h2d_exclusive`` (an upload point created it for exactly one
    downstream consumer — us — so no other reader exists)."""
    meta = getattr(buf, "meta", None)
    if meta is None or not hasattr(meta, "pop"):
        return
    stash = meta.pop(POOL_STASH_META, None)
    if stash:
        from nnstreamer_tpu.tensors.pool import get_pool

        get_pool().release_many(stash)
    if meta.pop(H2D_EXCLUSIVE_META, None):
        tensors = getattr(buf, "tensors", None)
        if tensors and all(is_device_array(t) for t in tensors):
            tensors.clear()


class DispatchWindow:
    """Per-element window of outstanding (dispatched, unfenced) batches.

    Not thread-safe on its own: a window belongs to one element whose
    chain runs on one streaming thread at a time (the same contract every
    element's ``chain`` already has).
    """

    def __init__(self, owner):
        #: weakly bound: the window must not keep a dead element (and its
        #: pipeline) alive through the metrics registry
        self._owner = weakref.ref(owner)
        self._entries: Deque[
            Tuple[List[Any], Optional[list], Optional[int], float]] = \
            collections.deque()
        self._m_fence = None
        self._m_poisoned = None
        self._gauge_done = False

    def __len__(self) -> int:
        return len(self._entries)

    def _inflight(self) -> int:
        owner = self._owner()
        if owner is None:
            return 1
        try:
            return max(0, int(owner.get_property("inflight")))
        except (KeyError, TypeError, ValueError):
            return 2

    def _obs(self):
        if self._m_fence is None:
            owner = self._owner()
            if owner is None:
                return None
            from nnstreamer_tpu.obs import get_registry

            reg = get_registry()
            labels = owner._obs_labels()
            self._m_fence = reg.histogram(
                "nns_filter_fence_wait_seconds",
                "Time blocked fencing the oldest outstanding dispatch "
                "(window full or EOS)", **labels)
            if not self._gauge_done:
                ref = weakref.ref(self)
                reg.gauge(
                    "nns_filter_inflight",
                    "Dispatched device batches currently outstanding",
                    fn=lambda: (len(ref()) if ref() is not None else 0),
                    **labels)
                self._gauge_done = True
        return self._m_fence

    # -- hot path -----------------------------------------------------------
    def admit(self, tensors: List[Any],
              stash: Optional[list] = None,
              frame: Optional[int] = None) -> None:
        """Register a just-dispatched batch; fence the oldest entries
        until at most ``inflight`` remain outstanding. Accepts a raw
        tensor list or a whole (Device)Buffer — a device-resident input
        arrived with no H2D stage and no pool stash, so its entry is
        purely an ordering fence. ``frame`` is the frame's trace seq so
        the timeline can draw the inflight slot as an async span."""
        tensors = getattr(tensors, "tensors", tensors)
        t_admit = time.monotonic()
        self._entries.append((list(tensors), stash, frame, t_admit))
        tl = _timeline.ACTIVE
        if tl is not None and frame is not None:
            tl.async_begin("inflight", frame, t_admit)
        limit = self._inflight()
        while len(self._entries) > limit:
            self._fence_oldest()

    def _fence_oldest(self) -> None:
        """Fence the oldest outstanding batch. A failing fence (device
        error surfacing at ``block_until_ready``, or an injected
        ``dispatch.fence`` fault) poisons ONLY that batch: the entry is
        already popped, its stash still releases, the timeline still
        closes its inflight span — the entries behind it fence normally
        on later calls, so in-order delivery of the surviving frames is
        never corrupted. The wrapped error propagates to the dispatching
        element's chain, where its error policy decides the outcome."""
        tensors, stash, frame, _t_admit = self._entries.popleft()
        hist = self._obs()
        t0 = time.monotonic()
        err: Optional[BaseException] = None
        try:
            fi = _faults.ACTIVE
            if fi is not None:
                # chaos hook: kind=stall parks this fence (watchdog
                # bait); kind=raise poisons the batch
                fi.check("dispatch.fence", seq=frame)
            for t in tensors:
                if is_device_array(t):
                    t.block_until_ready()
        except Exception as e:  # noqa: BLE001 — isolation: bookkeeping
            # below must run before the poisoned batch's error surfaces
            err = e
        t1 = time.monotonic()
        if hist is not None:
            hist.observe(t1 - t0)
        tl = _timeline.ACTIVE
        if tl is not None and frame is not None:
            tl.span("fence_wait", frame, t0, t1, track="dispatch")
            tl.async_end("inflight", frame, t1)
        if stash:
            # the fenced dispatch (and the H2D feeding it) is complete:
            # its pooled host staging buffers have no readers left —
            # except a stash array adopted as a DeviceBuffer's cached
            # host view, which the pool keeps pinned (release refuses it)
            # until that wrapper dies
            from nnstreamer_tpu.tensors.pool import get_pool

            get_pool().release_many(stash)
        if err is not None:
            self._count_poisoned()
            from nnstreamer_tpu.pipeline.element import FlowError

            owner = self._owner()
            name = owner.name if owner is not None else "dispatch"
            if isinstance(err, FlowError):
                raise err
            raise FlowError(
                f"{name}: poisoned in-flight batch at fence: {err}"
            ) from err

    def _count_poisoned(self) -> None:
        if self._m_poisoned is None:
            from nnstreamer_tpu.obs import get_registry

            owner = self._owner()
            labels = owner._obs_labels() if owner is not None else {}
            self._m_poisoned = get_registry().counter(
                "nns_fault_poisoned_batches_total",
                "In-flight dispatches whose fence failed (batch "
                "isolated; entries behind it fence normally)", **labels)
        self._m_poisoned.inc()

    def drain(self, on_error: str = "raise") -> None:
        """Fence everything outstanding (EOS / stop / unsplice). A
        poisoned batch never strands the entries behind it: every entry
        is fenced (stashes released) and the FIRST failure re-raises at
        the end — or is only logged with ``on_error="log"``, the
        teardown mode where a raise would abort the rest of stop()."""
        first: Optional[BaseException] = None
        while self._entries:
            try:
                self._fence_oldest()
            except Exception as e:  # noqa: BLE001 — keep fencing: the
                # remaining entries' stashes must still release
                if first is None:
                    first = e
        if first is not None:
            if on_error == "log":
                log.warning("dispatch drain: poisoned batch during "
                            "teardown: %s", first)
                return
            raise first

    def snapshot(self) -> dict:
        out = {"inflight_now": len(self._entries),
               "inflight_limit": self._inflight()}
        h = self._m_fence
        if h is not None and h.count:
            out["fence_wait_p50_ms"] = round(
                (h.percentile(50) or 0.0) * 1e3, 3)
            out["fence_wait_p99_ms"] = round(
                (h.percentile(99) or 0.0) * 1e3, 3)
        return out
