"""Serving continuity — the lifetime-boundary resilience layer.

PRs 8/11/12 made a pipeline survive faults *within* a process lifetime;
this module makes the serving plane survive the lifetime boundary
itself. Three legs, each with an exact kill switch:

- **Epoch-based live reconfiguration** (:func:`swap_model`).
  ``Pipeline.swap_model(filter_name, model=..., weights=...)`` promotes
  the per-filter ``reload_model`` event to a pipeline-level *versioned*
  swap: the owning dispatch window drains (the fence is the cutover
  point — every in-flight batch completes against the old epoch), the
  new backend/params install under a bumped epoch, the affected fused
  region invalidates exactly once, and the next frame serves the new
  model. Zero frames are dropped because nothing is removed from the
  stream: frames dispatched before the cutover used the old program,
  frames after use the new one, so output is byte-identical up to the
  cutover seq. A params-only swap (``weights=``) is a consts swap —
  the fused executable is reused with no XLA recompile; a model swap
  re-jits exactly once. No swap call ⇒ none of this code runs.

- **Checkpoint / restore** (:func:`checkpoint` / :func:`restore`).
  Serializes the *durable serving state* a restarted process would
  otherwise re-learn from cold: tensor_repo slots (recurrent stream
  state), the SLO scheduler's service-rate EWMAs and AIMD knobs, the
  residency manager's LRU order, and the flight recorder's P² quantile
  markers + attribution ring. Armed by ``NNSTPU_CHECKPOINT=<dir>`` /
  ``--checkpoint-dir`` / ``Pipeline.checkpoint_dir``; unset means not
  one byte of this path executes (a single env read in start/stop).
  Monotonic-clock anchors (completion spacing, burn-window event
  times, controller step timers) are deliberately NOT restored — they
  are meaningless in a new process and re-anchor on the first
  observation.

- **Persistent compilation cache** (:func:`enable_compile_cache`).
  Arms JAX's persistent compilation cache so the second boot of the
  same pipeline performs zero XLA compilations on the serving path.
  Hits/misses surface as ``nns_compile_cache_hits_total`` /
  ``nns_compile_cache_misses_total`` via JAX's monitoring events; a
  per-fused-region program-signature manifest (``programs.json``)
  rides in the cache dir so operators can audit what the cache is
  keyed on. ``NNSTPU_COMPILE_CACHE=<dir>`` arms it standalone; an
  armed checkpoint dir defaults the cache into ``<dir>/xla-cache``.

See docs/robustness.md, "Serving continuity".
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

from nnstreamer_tpu.log import get_logger

log = get_logger("continuity")

CHECKPOINT_ENV = "NNSTPU_CHECKPOINT"
CACHE_ENV = "NNSTPU_COMPILE_CACHE"

#: checkpoint state file name inside the checkpoint dir
STATE_FILE = "serving_state.pkl"
#: fused-region program-signature manifest inside the compile-cache dir
MANIFEST_FILE = "programs.json"
#: default compile-cache subdir when only a checkpoint dir is armed
CACHE_SUBDIR = "xla-cache"

#: state-file schema version — bump on any incompatible change
STATE_VERSION = 1

# --------------------------------------------------------------------------
# persistent compilation cache
# --------------------------------------------------------------------------
_cache_lock = threading.Lock()
_cache_dir: Optional[str] = None
_listener_installed = False
_metrics: Optional[Dict[str, Any]] = None

#: the JAX monitoring event names the hit/miss counters listen for
_EVENT_HIT = "/jax/compilation_cache/cache_hits"
_EVENT_MISS = "/jax/compilation_cache/cache_misses"


def cache_metrics() -> Dict[str, Any]:
    """Lazy shared counters (reads are safe from the listener thread)."""
    global _metrics
    if _metrics is None:
        with _cache_lock:
            if _metrics is None:
                from nnstreamer_tpu.obs import get_registry

                reg = get_registry()
                _metrics = {
                    "hits": reg.counter(
                        "nns_compile_cache_hits_total",
                        "XLA compilations served from the persistent "
                        "compile cache (warm boot: no compile happened)"),
                    "misses": reg.counter(
                        "nns_compile_cache_misses_total",
                        "XLA compilations the persistent cache could not "
                        "serve (a real compile ran and was written back)"),
                }
    return _metrics


def _on_jax_event(event: str, **kwargs) -> None:
    if event == _EVENT_HIT:
        cache_metrics()["hits"].inc()
    elif event == _EVENT_MISS:
        cache_metrics()["misses"].inc()


def compile_cache_dir() -> Optional[str]:
    """The armed cache directory, or None when the leg is off."""
    return _cache_dir


def cache_stats() -> Dict[str, int]:
    m = cache_metrics()
    return {"hits": int(m["hits"].value), "misses": int(m["misses"].value)}


def enable_compile_cache(directory: str) -> str:
    """Arm JAX's persistent compilation cache at ``directory``.

    Idempotent; re-arming with the same directory is a no-op. The size
    and compile-time floors are zeroed so CI-sized CPU programs persist
    too — the default floors exist to keep laptop caches small, but a
    serving cache wants every executable on the serving path."""
    global _cache_dir, _listener_installed
    directory = os.path.abspath(directory)
    with _cache_lock:
        if _cache_dir == directory:
            return directory
        os.makedirs(directory, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", directory)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            # JAX latches its use-the-cache decision at the first
            # compilation; arming after any jit has run (a warm import,
            # an earlier pipeline) would otherwise be silently inert
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except (ImportError, AttributeError):  # private API moved —
            # the cache still arms for processes that configure it
            # before their first compile
            pass
        if not _listener_installed:
            from jax._src import monitoring as _monitoring

            _monitoring.register_event_listener(_on_jax_event)
            _listener_installed = True
        _cache_dir = directory
    log.info("persistent compile cache armed at %s", directory)
    return directory


def maybe_enable_compile_cache_env(pipeline=None) -> Optional[str]:
    """``Pipeline.start()`` hook: arm the cache from ``NNSTPU_COMPILE_CACHE``,
    or default it into an armed checkpoint dir's ``xla-cache`` subdir.
    Both unset ⇒ two env reads, nothing else runs (the kill switch)."""
    spec = os.environ.get(CACHE_ENV, "").strip()
    ckpt = None if spec else _effective_checkpoint_dir(pipeline)
    target = spec or (os.path.join(ckpt, CACHE_SUBDIR) if ckpt else None)
    if not target:
        return None
    try:
        return enable_compile_cache(target)
    except OSError as e:  # an uncreatable cache dir must not fail
        # Pipeline.start() — serving continues cold, which is exactly
        # what an unarmed cache does
        log.warning("compile cache dir %s unusable: %s", target, e)
        return None


def region_signature(region) -> Dict[str, Any]:
    """A stable, auditable signature of one fused region's program: the
    member lineup plus the model/option properties that decide what gets
    traced. (The byte-exact cache key is XLA's own HLO hash — this
    manifest row is the operator-readable view of what maps to it.)"""
    members = []
    for m in getattr(region, "members", ()):
        members.append({
            "name": m.name,
            "type": getattr(m, "ELEMENT_NAME", type(m).__name__),
            "model": m._props.get("model"),
            "custom": m._props.get("custom"),
            "option": m._props.get("option"),
        })
    blob = json.dumps(members, sort_keys=True, default=str)
    return {
        "region": getattr(region, "name", "?"),
        "members": members,
        "signature": hashlib.sha256(blob.encode()).hexdigest()[:16],
    }


def write_program_manifest(pipe) -> Optional[str]:
    """Write the per-fused-region program-signature manifest into the
    armed cache dir. No cache dir or no regions ⇒ None."""
    directory = _cache_dir
    regions = [r for r in (getattr(pipe, "_regions", None) or ())
               if not getattr(r, "_dead", False)]
    if not directory or not regions:
        return None
    wall_written = time.time()  # export timestamp, not a duration
    doc = {
        "pipeline": pipe.name,
        "written_at": wall_written,
        "programs": [region_signature(r) for r in regions],
    }
    path = os.path.join(directory, MANIFEST_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)  # atomic publish
    return path


# --------------------------------------------------------------------------
# epoch-based live reconfiguration
# --------------------------------------------------------------------------
_swap_metric = None


def _count_swap() -> None:
    global _swap_metric
    if _swap_metric is None:
        from nnstreamer_tpu.obs import get_registry

        _swap_metric = get_registry().counter(
            "nns_model_swaps_total",
            "Pipeline-level live model swaps (epoch cutovers)")
    _swap_metric.inc()


def swap_model(pipe, filter_name: str, model: Optional[str] = None,
               weights: Any = None) -> Dict[str, Any]:
    """Zero-downtime versioned model swap on a running pipeline.

    Sequence: (1) drain the owning dispatch window — the fence is the
    cutover point, every in-flight batch completes against the old
    epoch; (2) install the new model/params under a bumped epoch (a
    weights-only swap re-registers the HBM residency unit under the new
    epoch key and retires the old epoch's unit, so ``nns_mem_used_bytes``
    nets out); (3) invalidate the owning fused region exactly once, so
    the next frame re-pulls stages — a params-only swap reuses the
    traced executable (no XLA recompile), a model-function swap re-jits
    once. Frames keep flowing throughout: nothing is dropped, output is
    byte-identical up to the cutover seq.
    """
    if model is None and weights is None:
        raise ValueError("swap_model: need model=, weights=, or both")
    el = pipe.by_name.get(filter_name)
    if el is None:
        raise KeyError(f"swap_model: no element {filter_name!r} in "
                       f"{pipe.name}")
    if not hasattr(el, "fw"):
        raise TypeError(f"swap_model: {filter_name!r} is not a "
                        f"tensor_filter")
    epoch = int(getattr(el, "_swap_epoch", 0)) + 1
    region = getattr(el, "_fused_region", None)
    if region is not None and getattr(region, "_dead", False):
        region = None

    # 1. fence: every outstanding dispatch against the old epoch retires
    #    before the new one installs — the cutover is between frames
    window = getattr(region if region is not None else el, "_window", None)
    if window is not None:
        window.drain()

    report: Dict[str, Any] = {
        "filter": filter_name, "epoch": epoch, "model": model,
        "weights": weights is not None, "invalidations": 0,
        "residency_unit": None, "retired_unit": None,
    }

    # 2. install under the new epoch
    fw = el.fw
    if model is not None:
        el._props["model"] = model
        if fw is not None:
            fw.handle_event("reload_model", {"model": model})
            el._obs_invoke()["reloads"].inc()
    if weights is not None:
        if fw is None:
            raise RuntimeError(f"swap_model: {filter_name!r} has no open "
                               f"backend to install weights into")
        install = getattr(fw, "install_weights", None)
        if install is None:
            raise RuntimeError(
                f"swap_model: backend {type(fw).__name__} does not "
                f"support in-place weight swaps")
        res = install(weights, epoch=epoch)
        report["residency_unit"] = res.get("residency")
        report["retired_unit"] = res.get("retired")

    # 3. exactly one fused-region invalidation: the next frame re-pulls
    #    member stages (consts swap in place, or one re-jit if the model
    #    function changed — nns_fuse_retraces_total counts that at trace
    #    time, never here)
    if region is not None:
        region.invalidate()
        report["invalidations"] = 1

    el._swap_epoch = epoch
    _count_swap()
    from nnstreamer_tpu.obs import timeline as _timeline

    tl = _timeline.ACTIVE
    if tl is not None:
        tl.mark("model_swap", None, track="continuity",
                filter=filter_name, epoch=epoch,
                consts_only=(model is None))
    log.info("%s: swapped %s to epoch %d (%s)", pipe.name, filter_name,
             epoch, "weights only" if model is None else model)
    return report


# --------------------------------------------------------------------------
# checkpoint / restore
# --------------------------------------------------------------------------
def _effective_checkpoint_dir(pipe, directory: Optional[str] = None
                              ) -> Optional[str]:
    if directory:
        return directory
    if pipe is not None and getattr(pipe, "checkpoint_dir", None):
        return pipe.checkpoint_dir
    env = os.environ.get(CHECKPOINT_ENV, "").strip()
    return env or None


def _query_servers(pipe):
    """Elements carrying a live query server (tensor_query_serversrc)."""
    out = []
    for el in getattr(pipe, "elements", ()):
        srv = getattr(el, "server", None) or getattr(el, "_server", None)
        if srv is not None and hasattr(srv, "checkpoint_state"):
            out.append((el.name, srv))
    return out


def checkpoint(pipe, directory: Optional[str] = None) -> str:
    """Serialize the pipeline's durable serving state into
    ``<dir>/serving_state.pkl`` (atomic publish) and refresh the
    program-signature manifest. Returns the state-file path."""
    directory = _effective_checkpoint_dir(pipe, directory)
    if not directory:
        raise ValueError(
            "checkpoint: no directory (pass one, set "
            "Pipeline.checkpoint_dir, or export NNSTPU_CHECKPOINT)")
    os.makedirs(directory, exist_ok=True)
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO
    from nnstreamer_tpu.tensors import memory as _memory

    wall_saved = time.time()  # export timestamp, not a duration
    sched = getattr(pipe, "_slo_scheduler", None)
    fr = getattr(pipe, "_flight", None)
    acct = _memory.ACTIVE
    state: Dict[str, Any] = {
        "version": STATE_VERSION,
        "pipeline": pipe.name,
        "wall_saved": wall_saved,
        "repo": GLOBAL_REPO.snapshot(),
        "scheduler": sched.checkpoint_state() if sched is not None
        else None,
        "flight": fr.checkpoint_state() if fr is not None else None,
        "residency": acct.residency.checkpoint_state()
        if acct is not None else None,
        "servers": {name: srv.checkpoint_state()
                    for name, srv in _query_servers(pipe)},
        "swap_epochs": {el.name: int(el._swap_epoch)
                        for el in pipe.elements
                        if getattr(el, "_swap_epoch", 0)},
        "compile_cache_dir": _cache_dir,
    }
    path = os.path.join(directory, STATE_FILE)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)  # atomic publish — a killed writer leaves the
    # previous checkpoint intact, never a torn one
    write_program_manifest(pipe)
    log.info("%s: checkpoint written to %s", pipe.name, path)
    return path


def restore(pipe, directory: Optional[str] = None) -> Dict[str, Any]:
    """Load ``<dir>/serving_state.pkl`` and re-arm the warm serving
    state: repo slots, scheduler estimates/knobs, residency LRU order,
    flight-recorder quantiles, query-server dedup windows, swap epochs,
    and the persistent compile cache. Returns a summary of what was
    applied."""
    directory = _effective_checkpoint_dir(pipe, directory)
    if not directory:
        raise ValueError(
            "restore: no directory (pass one, set "
            "Pipeline.checkpoint_dir, or export NNSTPU_CHECKPOINT)")
    path = os.path.join(directory, STATE_FILE)
    with open(path, "rb") as f:
        state = pickle.load(f)
    if state.get("version") != STATE_VERSION:
        raise ValueError(
            f"restore: state version {state.get('version')!r} != "
            f"{STATE_VERSION} (checkpoint from an incompatible build)")
    applied: Dict[str, Any] = {"path": path, "pipeline": state["pipeline"]}
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO
    from nnstreamer_tpu.tensors import memory as _memory

    repo_state = state.get("repo")
    if repo_state:
        GLOBAL_REPO.restore(repo_state)
        applied["repo_slots"] = len(repo_state)
    sched = getattr(pipe, "_slo_scheduler", None)
    if sched is not None and state.get("scheduler"):
        sched.restore_state(state["scheduler"])
        applied["scheduler"] = True
    fr = getattr(pipe, "_flight", None)
    if fr is not None and state.get("flight"):
        fr.restore_state(state["flight"])
        applied["flight"] = True
    acct = _memory.ACTIVE
    if acct is not None and state.get("residency"):
        acct.residency.restore_state(state["residency"])
        applied["residency"] = True
    servers = dict(_query_servers(pipe))
    for name, srv_state in (state.get("servers") or {}).items():
        srv = servers.get(name)
        if srv is not None:
            srv.restore_state(srv_state)
            applied.setdefault("servers", []).append(name)
    for name, epoch in (state.get("swap_epochs") or {}).items():
        el = pipe.by_name.get(name)
        if el is not None:
            el._swap_epoch = int(epoch)
    cache = state.get("compile_cache_dir")
    if cache and os.path.isdir(cache):
        enable_compile_cache(cache)
        applied["compile_cache_dir"] = cache
    log.info("%s: restored serving state from %s (%s)", pipe.name, path,
             ", ".join(k for k in applied if k not in ("path", "pipeline")))
    return applied


def maybe_restore_env(pipe) -> Optional[Dict[str, Any]]:
    """``Pipeline.start()`` hook: restore once from an armed checkpoint
    dir whose state file exists. Unset dir ⇒ one env read; armed dir
    with no state file (first boot) ⇒ one ``os.path.isfile``."""
    if getattr(pipe, "_continuity_restored", False):
        return None
    directory = _effective_checkpoint_dir(pipe)
    if not directory:
        return None
    path = os.path.join(directory, STATE_FILE)
    if not os.path.isfile(path):
        return None
    pipe._continuity_restored = True
    return restore(pipe, directory)


def maybe_checkpoint_on_stop(pipe) -> Optional[str]:
    """``Pipeline.stop()`` hook: write a checkpoint when armed. A
    failure to persist must never turn a clean shutdown into an error —
    it logs and returns None."""
    directory = _effective_checkpoint_dir(pipe)
    if not directory:
        return None
    try:
        return checkpoint(pipe, directory)
    except Exception as e:  # noqa: BLE001 — a full disk or unpicklable
        # payload must not fail teardown; the previous checkpoint (if
        # any) is still intact thanks to the atomic publish
        log.warning("%s: checkpoint on stop failed: %s", pipe.name, e)
        return None
