"""Caps — typed stream capabilities and negotiation.

The reference negotiates pad formats with GStreamer caps: a media-type name
plus fields whose values can be fixed, lists, or ranges; linking intersects
upstream and downstream caps and fixates the result
(``tensor_common.c`` caps helpers, ``gst_tensor_filter_configure_tensor``,
tensor_filter.c:794). We keep the same model because it is what lets
semantics-agnostic elements compose, but the implementation is a small
value-type: a name plus a field dict where a value may be

- a fixed scalar (int/str/Fraction),
- a list of alternatives,
- an ``IntRange(lo, hi)``,
- or ``ANY`` (unconstrained).

Intersection is field-wise; a missing field means unconstrained. ``fixate``
collapses lists/ranges to their first/lowest value. This is deliberately much
smaller than GstCaps — tensor pipelines only ever use a handful of fields —
while preserving the negotiation semantics the elements rely on.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Optional

ANY = object()


@dataclasses.dataclass(frozen=True)
class IntRange:
    lo: int
    hi: int

    def intersect(self, other):
        if isinstance(other, IntRange):
            lo, hi = max(self.lo, other.lo), min(self.hi, other.hi)
            if lo > hi:
                return None
            return IntRange(lo, hi) if lo != hi else lo
        if isinstance(other, int):
            return other if self.lo <= other <= self.hi else None
        return None

    def __contains__(self, v):
        return isinstance(v, int) and self.lo <= v <= self.hi


def _intersect_values(a, b):
    """Intersect two field values; None means empty intersection."""
    if a is ANY:
        return b
    if b is ANY:
        return a
    if isinstance(a, IntRange):
        return a.intersect(b)
    if isinstance(b, IntRange):
        return b.intersect(a)
    a_list = a if isinstance(a, (list, tuple)) else [a]
    b_list = b if isinstance(b, (list, tuple)) else [b]
    common = [x for x in a_list if x in b_list]
    if not common:
        return None
    return common[0] if len(common) == 1 else list(common)


def _is_fixed_value(v) -> bool:
    return v is not ANY and not isinstance(v, (list, IntRange))


class Caps:
    """One caps structure: media-type name + constraint fields."""

    def __init__(self, name: str, fields: Optional[Dict[str, Any]] = None):
        self.name = name
        self.fields: Dict[str, Any] = dict(fields or {})

    # -- mapping protocol ----------------------------------------------------
    def __getitem__(self, k):
        return self.fields[k]

    def __contains__(self, k):
        return k in self.fields

    def get(self, k, default=None):
        return self.fields.get(k, default)

    def with_fields(self, **kw) -> "Caps":
        f = dict(self.fields)
        f.update(kw)
        return Caps(self.name, f)

    def to_string(self) -> str:
        """GStreamer-style textual caps ("name,k=v,..."), the inverse of
        :func:`~nnstreamer_tpu.pipeline.parse.parse_caps_string` — the
        form caps travel in on query/MQTT wires (reference
        gst_caps_to_string)."""
        parts = [self.name]
        parts.extend(f"{k}={v}" for k, v in self.fields.items())
        return ",".join(parts)

    # -- negotiation ---------------------------------------------------------
    def intersect(self, other: "Caps") -> Optional["Caps"]:
        if self.name != other.name:
            return None
        fields = dict(self.fields)
        for k, v in other.fields.items():
            if k in fields:
                merged = _intersect_values(fields[k], v)
                if merged is None:
                    return None
                fields[k] = merged
            else:
                fields[k] = v
        return Caps(self.name, fields)

    def is_fixed(self) -> bool:
        return all(_is_fixed_value(v) for v in self.fields.values())

    def fixate(self) -> "Caps":
        fields = {}
        for k, v in self.fields.items():
            if v is ANY:
                continue
            if isinstance(v, list):
                v = v[0]
            elif isinstance(v, IntRange):
                v = v.lo
            fields[k] = v
        return Caps(self.name, fields)

    def __eq__(self, other):
        return (
            isinstance(other, Caps)
            and self.name == other.name
            and self.fields == other.fields
        )

    def __repr__(self):
        parts = [self.name]
        for k, v in self.fields.items():
            if v is ANY:
                v = "ANY"
            elif isinstance(v, IntRange):
                v = f"[{v.lo},{v.hi}]"
            parts.append(f"{k}={v}")
        return "Caps(" + ", ".join(str(p) for p in parts) + ")"


class CapsList:
    """An ordered set of alternative Caps (a pad template's full caps).

    An ANY CapsList (unconstrained pad) is distinct from an *empty* one
    (failed negotiation) — gst makes the same distinction between
    GST_CAPS_ANY and empty caps.
    """

    def __init__(self, caps: Iterable[Caps], _any: bool = False):
        self.caps = list(caps)
        self._any = _any and not self.caps

    @classmethod
    def any(cls) -> "CapsList":
        return cls([], _any=True)

    def is_any(self) -> bool:
        return self._any

    def intersect(self, other: "CapsList") -> "CapsList":
        if self.is_any():
            return CapsList(other.caps, _any=other.is_any())
        if other.is_any():
            return CapsList(self.caps)
        out = []
        for a in self.caps:
            for b in other.caps:
                c = a.intersect(b)
                if c is not None:
                    out.append(c)
        return CapsList(out)

    def is_empty(self) -> bool:
        return not self.is_any() and not self.caps

    def first(self) -> Optional[Caps]:
        return self.caps[0] if self.caps else None

    def __iter__(self):
        return iter(self.caps)

    def __repr__(self):
        return f"CapsList({self.caps!r})" if self.caps else "CapsList(ANY)"
