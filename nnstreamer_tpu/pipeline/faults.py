"""Deterministic fault injection — the chaos half of the supervision layer.

A production serving plane is defined by what it does when things break,
and "what it does" is untestable without a way to break things on
demand, repeatably. This module is a seeded, spec-driven injector with
hooks at the five places the async substrate can actually fail:

- ``filter.invoke``   — backend invoke in ``elements/filter.py``
- ``filter.open``     — backend open / weight load (``elements/filter.py``)
- ``transfer.h2d``    — host→device upload (``tensors/buffer.py``)
- ``transfer.d2h``    — device→host materialization (``tensors/buffer.py``)
- ``pool.alloc``      — pool slab growth (``tensors/pool.py``)
- ``lane.worker``     — per-frame lane worker loop (``pipeline/lanes.py``)
- ``queue.push``      — queue ingress (``pipeline/pipeline.py``)
- ``dispatch.fence``  — dispatch-window fence (``pipeline/dispatch.py``)

plus the transport sites, where the network itself is the failure
domain (the resilience layer, ``query/resilience.py``, is what's under
test there):

- ``query.send``      — query-client frame send (``elements/query.py``)
- ``query.recv``      — query-client result receive (``elements/query.py``)
- ``grpc.call``       — TensorService stream call (``query/grpc_bridge.py``)
- ``mqtt.publish``    — MQTT publish (``query/mqtt.py``)

Spec grammar (``NNSTPU_FAULTS``)::

    site:key=val,key=val;site:key=val,...

    NNSTPU_FAULTS="filter.invoke:rate=0.01,kind=raise;\
    lane.worker:nth=37,kind=crash;dispatch.fence:kind=stall,ms=500"

Per-site keys:

- ``kind``  — ``raise`` (ordinary exception, recoverable under an
  error-policy), ``crash`` (simulated abrupt worker death — lane
  supervision treats it as a restart, everything else like ``raise``),
  ``stall`` (sleep ``ms`` milliseconds — watchdog bait), ``oom``
  (simulated device-memory exhaustion — raises :class:`InjectedOom`,
  which the supervision layer's memory-pressure ladder recovers: evict
  residency units → release pools → shed at admission → CPU fallback;
  see ``tensors/memory.py`` and docs/robustness.md), or one of the
  transport kinds ``drop`` (the bytes silently vanish), ``disconnect``
  (the connection dies mid-operation), ``corrupt`` (the bytes arrive
  mangled). Transport kinds are interpreted by :meth:`FaultInjector.
  action` hooks; at a :meth:`FaultInjector.check` hook (the compute
  sites) they degrade to ``raise`` — a drop has no meaning for a
  backend invoke.
- trigger — exactly one of ``rate=<float>`` (seeded Bernoulli per
  occurrence), ``nth=<int>`` (fire on exactly the nth occurrence,
  1-based), or ``every=<int>`` (every k·every-th occurrence).
- ``ms``    — stall duration (``kind=stall`` only), default 100.
- ``seed``  — per-site seed override; else ``NNSTPU_FAULTS_SEED``
  (default 0).

Determinism contract: the decision for the *n*-th occurrence at a site
is a pure function of ``(seed, site, n)`` — independent of thread
interleaving — so the same spec + seed reproduces the same fired set
across runs even with parallel lanes racing on the counters.

Kill-switch discipline (same as ``obs/timeline.py``): the process-wide
:data:`ACTIVE` injector is ``None`` by default; every hook site is one
module-attribute read and an ``is None`` test, so the unset path stays
byte-identical to a build without this module. ``Pipeline.start()``
honors the env via :func:`maybe_activate_env`.

Every fired fault increments ``nns_fault_injected_total{site,kind}``
and drops a ``fault`` mark on the frame ledger (``obs/timeline.py``),
so tests can assert injected counts from three independent witnesses:
the injector's log, the metric, and the trace.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline

log = get_logger("faults")

_ENV = "NNSTPU_FAULTS"
_ENV_SEED = "NNSTPU_FAULTS_SEED"

#: the injection-hook sites wired through the async substrate
SITES: Tuple[str, ...] = ("filter.invoke", "filter.open",
                          "transfer.h2d", "transfer.d2h", "pool.alloc",
                          "lane.worker", "queue.push", "dispatch.fence",
                          "query.send", "query.recv", "grpc.call",
                          "mqtt.publish")

KINDS: Tuple[str, ...] = ("raise", "crash", "stall", "oom",
                          "drop", "disconnect", "corrupt")

#: kinds a transport hook interprets itself (returned by :meth:`action`)
#: rather than having raised at it
ACTION_KINDS: Tuple[str, ...] = ("drop", "disconnect", "corrupt")

#: the process-wide injector; ``None`` (default) means injection is OFF
#: and every hook site reduces to one attribute read + is-None test
ACTIVE: Optional["FaultInjector"] = None


class InjectedFault(RuntimeError):
    """An injector-raised failure (``kind=raise``). Deliberately an
    ordinary exception: recovery machinery must not special-case it."""

    def __init__(self, site: str, n: int, kind: str = "raise"):
        super().__init__(f"injected fault at {site} (occurrence {n})")
        self.site = site
        self.n = n
        self.kind = kind


class InjectedCrash(InjectedFault):
    """``kind=crash``: simulated abrupt worker death. Lane supervision
    restarts the worker's clone chain on this (no per-frame retry of a
    corpse); everywhere else it behaves like :class:`InjectedFault`."""

    def __init__(self, site: str, n: int):
        super().__init__(site, n, kind="crash")


class InjectedOom(InjectedFault):
    """``kind=oom``: simulated device-memory exhaustion (the shape of a
    real ``RESOURCE_EXHAUSTED``). Under ``error-policy=degrade`` the
    supervision layer routes this through the memory-pressure ladder
    (evict → pool → shed → cpu) instead of the plain reload ladder;
    everywhere else it behaves like :class:`InjectedFault`."""

    def __init__(self, site: str, n: int):
        super().__init__(site, n, kind="oom")


@dataclasses.dataclass
class FaultRule:
    """One parsed ``site:...`` clause of the spec."""

    site: str
    kind: str = "raise"
    rate: float = 0.0
    nth: Optional[int] = None
    every: Optional[int] = None
    ms: float = 100.0
    seed: Optional[int] = None


def parse_faults(spec: str) -> List[FaultRule]:
    """Parse the ``NNSTPU_FAULTS`` grammar. Raises ``ValueError`` on an
    unknown site/kind/key — a typo'd chaos spec that silently injects
    nothing would report "system survives faults" vacuously."""
    rules: List[FaultRule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        site, _, body = clause.partition(":")
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"NNSTPU_FAULTS: unknown site {site!r} (sites: "
                f"{', '.join(SITES)})")
        rule = FaultRule(site=site)
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            key, _, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if key == "kind":
                if val not in KINDS:
                    raise ValueError(
                        f"NNSTPU_FAULTS: unknown kind {val!r} at {site} "
                        f"(kinds: {', '.join(KINDS)})")
                rule.kind = val
            elif key == "rate":
                rule.rate = float(val)
            elif key == "nth":
                rule.nth = int(val)
            elif key == "every":
                rule.every = max(1, int(val))
            elif key == "ms":
                rule.ms = float(val)
            elif key == "seed":
                rule.seed = int(val)
            else:
                raise ValueError(
                    f"NNSTPU_FAULTS: unknown key {key!r} at {site} "
                    f"(keys: kind, rate, nth, every, ms, seed)")
        rules.append(rule)
    return rules


class FaultInjector:
    """Spec-driven deterministic injector.

    One occurrence counter per site (under a lock — lane workers hit
    their site concurrently); the fire decision for occurrence ``n`` is
    a pure function of ``(seed, site, n)``, so the fired set is
    reproducible regardless of thread interleaving."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self._rules: Dict[str, FaultRule] = {r.site: r for r in rules}
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        #: every fired fault as ``(site, occurrence, kind)``, in fire
        #: order per site — the determinism tests' ground truth
        self.fired: List[Tuple[str, int, str]] = []
        self._m = None  # lazy: {(site, kind): Counter}

    # -- observation ---------------------------------------------------------
    def _count_metric(self, site: str, kind: str) -> None:
        if self._m is None:
            self._m = {}
        key = (site, kind)
        c = self._m.get(key)
        if c is None:
            from nnstreamer_tpu.obs import get_registry

            c = self._m[key] = get_registry().counter(
                "nns_fault_injected_total",
                "Faults fired by the deterministic injector "
                "(pipeline/faults.py)", site=site, kind=kind)
        c.inc()

    def injected(self, site: Optional[str] = None) -> int:
        """Fired-fault count, total or per site."""
        with self._lock:
            if site is None:
                return len(self.fired)
            return sum(1 for s, _n, _k in self.fired if s == site)

    def fired_set(self, site: str) -> List[int]:
        """The occurrence indices that fired at ``site`` (sorted) — two
        runs with the same spec + seed must produce the same list."""
        with self._lock:
            return sorted(n for s, n, _k in self.fired if s == site)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for s, _n, _k in self.fired:
                out[s] = out.get(s, 0) + 1
            return out

    # -- hot path ------------------------------------------------------------
    def _decide(self, rule: FaultRule, n: int) -> bool:
        if rule.nth is not None:
            return n == rule.nth
        if rule.every is not None:
            return n % rule.every == 0
        if rule.rate > 0.0:
            seed = rule.seed if rule.seed is not None else self.seed
            # a STRING seed hashes via sha512 — stable across processes
            # (a tuple seed would go through hash(), which PYTHONHASHSEED
            # randomizes per process, silently breaking cross-run
            # reproducibility)
            rng = random.Random(f"{seed}:{rule.site}:{n}")
            return rng.random() < rule.rate
        return False

    def _fire(self, site: str, seq: Optional[int]
              ) -> Optional[Tuple[int, FaultRule]]:
        """Count the occurrence and decide; on fire, log/meter/mark and
        return ``(n, rule)`` for the caller to act on. The decision for
        occurrence ``n`` stays the same pure function of
        ``(seed, site, n)`` regardless of which hook entry counted it."""
        rule = self._rules.get(site)
        if rule is None:
            return None
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
        if not self._decide(rule, n):
            return None
        with self._lock:
            self.fired.append((site, n, rule.kind))
        self._count_metric(site, rule.kind)
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("fault", seq, track="faults", site=site,
                    fault_kind=rule.kind, n=n)
        log.info("fault injected: site=%s kind=%s occurrence=%d seq=%s",
                 site, rule.kind, n, seq)
        return n, rule

    def check(self, site: str, seq: Optional[int] = None) -> None:
        """The compute-site hook entry: count the occurrence, fire per
        the rule. ``raise``/``crash`` raise; ``stall`` sleeps ``ms`` and
        returns; the transport kinds degrade to ``raise`` (a drop has no
        meaning mid-invoke). ``seq`` is the frame-ledger id for the
        trace mark."""
        fired = self._fire(site, seq)
        if fired is None:
            return
        n, rule = fired
        if rule.kind == "stall":
            time.sleep(rule.ms / 1e3)
            return
        if rule.kind == "crash":
            raise InjectedCrash(site, n)
        if rule.kind == "oom":
            raise InjectedOom(site, n)
        raise InjectedFault(site, n, kind=rule.kind)

    def action(self, site: str, seq: Optional[int] = None) -> Optional[str]:
        """The transport-site hook entry: like :meth:`check`, but the
        kinds a transport can act out itself come back as a verdict —
        ``"drop"`` / ``"disconnect"`` / ``"corrupt"`` — for the hook to
        interpret (swallow the send, kill the socket, mangle the bytes).
        ``None`` means no fault fired; ``stall`` sleeps here and returns
        ``None``; ``raise``/``crash`` raise exactly as at a check
        site."""
        fired = self._fire(site, seq)
        if fired is None:
            return None
        n, rule = fired
        if rule.kind == "stall":
            time.sleep(rule.ms / 1e3)
            return None
        if rule.kind == "crash":
            raise InjectedCrash(site, n)
        if rule.kind == "oom":
            raise InjectedOom(site, n)
        if rule.kind == "raise":
            raise InjectedFault(site, n)
        return rule.kind


# --------------------------------------------------------------------------
# activation (timeline.ACTIVE-style kill switch)
# --------------------------------------------------------------------------
def activate(spec: str, seed: int = 0) -> FaultInjector:
    """Install a process-wide injector from a spec string."""
    global ACTIVE
    inj = FaultInjector(parse_faults(spec), seed=seed)
    ACTIVE = inj
    return inj


def deactivate() -> None:
    global ACTIVE
    ACTIVE = None


def maybe_activate_env() -> Optional[FaultInjector]:
    """``Pipeline.start()`` hook: honor ``NNSTPU_FAULTS`` /
    ``NNSTPU_FAULTS_SEED`` without code changes. Idempotent; an
    explicitly installed injector wins; unset env leaves :data:`ACTIVE`
    ``None`` — the byte-identical off path."""
    if ACTIVE is not None:
        return ACTIVE
    spec = os.environ.get(_ENV, "").strip()
    if not spec:
        return None
    raw_seed = os.environ.get(_ENV_SEED, "").strip()
    try:
        seed = int(raw_seed) if raw_seed else 0
    except ValueError:
        log.warning("%s=%r is not an int; using seed 0", _ENV_SEED,
                    raw_seed)
        seed = 0
    inj = activate(spec, seed=seed)
    log.info("fault injection active: %s (seed %d)", spec, seed)
    return inj
