"""L3 pipeline core: pads, elements, events, the pipeline scheduler, and the
gst-launch-style pipeline-description parser."""

from nnstreamer_tpu.pipeline.caps import Caps, CapsList, IntRange, ANY  # noqa: F401
from nnstreamer_tpu.pipeline.element import (  # noqa: F401
    Element,
    Pad,
    PadDirection,
    FlowReturn,
    Event,
    CapsEvent,
    EosEvent,
    CustomEvent,
    FlowError,
)
from nnstreamer_tpu.pipeline.pipeline import Pipeline  # noqa: F401
from nnstreamer_tpu.pipeline.parse import parse_launch  # noqa: F401
