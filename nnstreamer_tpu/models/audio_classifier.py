"""1-D conv audio classifier — the audio model family.

The reference streams audio through the same tensor pipeline as video
(`tensor_converter` chunks S16LE/F32LE samples, `tensor_aggregator`
windows them — gst/nnstreamer/tensor_converter audio path,
`tensor_aggregator/README.md`); its test suites use trivial custom
filters on audio caps. This gives the audio path a REAL model: a compact
keyword-spotting-style network (conv1d stack → global pool → dense),
MXU-friendly (channels stay multiples of 8, all matmul/conv work in
bfloat16 under jit).

Pipeline shape:
  audiotestsrc ! tensor_converter frames-per-tensor=16000 !
  tensor_transform mode=arithmetic option=typecast:float32,div:32768 !
  tensor_filter framework=jax model=kws ! tensor_decoder mode=image_labeling
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from nnstreamer_tpu.models._init import fast_init
from nnstreamer_tpu.tensors.types import TensorsInfo


class AudioClassifier(nn.Module):
    """Conv1D keyword-spotting classifier over a mono window."""

    num_classes: int = 12
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: [batch, samples, channels]
        h = x.astype(self.dtype)
        for i, stride in enumerate((4, 4, 2, 2)):
            h = nn.Conv(self.width * (1 + i // 2), kernel_size=(9,),
                        strides=(stride,), dtype=self.dtype)(h)
            h = nn.relu(h)
        h = h.mean(axis=1)  # global average pool over time
        h = nn.Dense(self.width * 2, dtype=self.dtype)(h)
        h = nn.relu(h)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(h)


def audio_classifier(samples: int = 16000, channels: int = 1,
                     num_classes: int = 12, batch: int = 1,
                     dtype=jnp.bfloat16, seed: int = 0
                     ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """(apply_fn, params, in_info, out_info) for the jax filter backend.

    in_info matches the converter's audio layout (samples × channels per
    frame); out_info is the class-logit vector the image_labeling decoder
    consumes (argmax → label, same contract as vision classifiers).
    """
    model = AudioClassifier(num_classes=num_classes, dtype=dtype)

    def apply_fn(params, x):
        if x.ndim == 2:  # converter emits [samples, ch]; add batch
            x = x[None]
        return model.apply(params, x.astype(jnp.float32))

    rng = jax.random.PRNGKey(seed)
    params = fast_init(model.init, rng,
                       jnp.zeros((batch, samples, channels), jnp.float32),
                       seed=seed)
    in_info = TensorsInfo.from_str(f"{channels}:{samples}", "float32")
    out_info = TensorsInfo.from_str(f"{num_classes}:1", "float32")
    return apply_fn, params, in_info, out_info
