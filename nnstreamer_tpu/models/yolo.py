"""YOLO-style single-head detector — pairs with the bounding_boxes
decoder's ``option1=yolov5`` mode (reference tensordec-boundingbox.c
yolov5 branch decodes [anchors, 5+classes] rows of cx,cy,w,h,objectness,
class-logits).

The reference consumes external yolov5 .tflite files; this is a native
flax detector with the same output contract so the full pipeline
(model → fused device NMS → overlay/meta) runs end-to-end on TPU.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from nnstreamer_tpu.models.mobilenet_v2 import InvertedResidual
from nnstreamer_tpu.tensors.types import TensorsInfo


class YoloDetector(nn.Module):
    num_classes: int = 80
    anchors_per_cell: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu6(nn.BatchNorm(use_running_average=True,
                                  dtype=self.dtype)(x))
        for expand, out_ch, repeats, stride in [
            (1, 16, 1, 1), (6, 32, 2, 2), (6, 64, 2, 2), (6, 128, 3, 2),
        ]:
            for i in range(repeats):
                x = InvertedResidual(out_ch, stride if i == 0 else 1,
                                     expand, self.dtype)(x)
        # one stride-16 head: [N, ch, cw, k*(5+C)] → [N, A, 5+C]
        k, c = self.anchors_per_cell, self.num_classes
        head = nn.Conv(k * (5 + c), (1, 1), dtype=self.dtype)(x)
        n = head.shape[0]
        pred = head.reshape(n, -1, 5 + c).astype(jnp.float32)
        # box center/size activations live in the decoder for the
        # reference contract: rows are (cx, cy, w, h, obj, cls...) with
        # obj/cls as logits; normalize cx,cy,w,h into [0,1] here
        ch, cw = x.shape[1], x.shape[2]
        grid = (jnp.arange(ch * cw) % cw).astype(jnp.float32)
        gy = (jnp.arange(ch * cw) // cw).astype(jnp.float32)
        gx = jnp.repeat(grid, k).reshape(1, -1)
        gyr = jnp.repeat(gy, k).reshape(1, -1)
        cx = (jax.nn.sigmoid(pred[:, :, 0]) + gx) / cw
        cy = (jax.nn.sigmoid(pred[:, :, 1]) + gyr) / ch
        w = jax.nn.sigmoid(pred[:, :, 2])
        h = jax.nn.sigmoid(pred[:, :, 3])
        return jnp.concatenate(
            [jnp.stack([cx, cy, w, h], axis=2), pred[:, :, 4:]], axis=2)


def yolo_detector(num_classes: int = 80, image_size: int = 320,
                  batch: int = 1, dtype=jnp.float32, seed: int = 0
                  ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """Factory: apply_fn(params, image[N,H,W,3]) → pred [N, A, 5+C] in the
    bounding_boxes yolov5 decoder contract."""
    model = YoloDetector(num_classes=num_classes, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    from nnstreamer_tpu.models._init import fast_init
    variables = fast_init(model.init, rng, dummy, seed=seed)
    pred = jax.eval_shape(lambda p, x: model.apply(p, x), variables, dummy)

    def apply_fn(params, x):
        return model.apply(params, x)

    in_info = TensorsInfo.from_str(
        f"3:{image_size}:{image_size}:{batch}", "float32")
    out_info = TensorsInfo.from_str(
        f"{pred.shape[2]}:{pred.shape[1]}:{batch}", "float32")
    return apply_fn, variables, in_info, out_info
