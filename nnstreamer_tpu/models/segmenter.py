"""Semantic segmentation model — feeds the image_segment decoder.

The reference decodes segmentation model outputs with its image_segment
subplugin (/root/reference/ext/nnstreamer/tensor_decoder/
tensordec-imagesegment.c) but ships no in-tree model; pipelines load
tflite deeplab builds. Here the model family is native flax — an
FCN/U-Net-style encoder-decoder sized for streaming, with TPU choices
matching the rest of the zoo (models/mobilenet_v2.py): NHWC, channels in
multiples of 8 for clean MXU tiling, bf16 activations with fp32 conv
accumulation, static shapes, per-pixel class logits at input resolution
(the image_segment decoder's expected layout, [b, H, W, classes]).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from nnstreamer_tpu.tensors.types import TensorsInfo


class _ConvBlock(nn.Module):
    ch: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        return nn.relu(x)


class Segmenter(nn.Module):
    """Encoder-decoder FCN with skip connections (U-Net shape, sized for
    streaming video rather than medical imagery)."""

    num_classes: int = 21  # VOC-style default
    base: int = 32         # stem width; doubles per stage
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        ch = self.base
        for _ in range(3):                     # encoder: /2 per stage
            x = _ConvBlock(ch, self.dtype)(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            ch *= 2
        x = _ConvBlock(ch, self.dtype)(x)      # bottleneck
        for skip in reversed(skips):           # decoder: ×2 per stage
            ch //= 2
            b, h, w, _ = skip.shape
            x = jax.image.resize(x, (b, h, w, x.shape[-1]), "nearest")
            x = nn.Conv(ch, (1, 1), use_bias=False, dtype=self.dtype)(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = _ConvBlock(ch, self.dtype)(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(x)
        return x.astype(jnp.float32)           # [b, H, W, classes]


def segmenter(num_classes: int = 21, base: int = 32, image_size: int = 256,
              batch: int = 1, dtype=jnp.bfloat16, seed: int = 0
              ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """Factory: (apply_fn, params, in_info, out_info).

    Input float32 NHWC (preprocessing belongs to tensor_transform, as in
    the reference pipelines); output per-pixel class logits that
    ``tensor_decoder mode=image_segment`` argmaxes on device.
    ``image_size`` must be divisible by 8 (three /2 encoder stages).
    """
    if image_size % 8:
        raise ValueError(
            f"segmenter: image_size must be divisible by 8, got "
            f"{image_size}")
    model = Segmenter(num_classes=num_classes, base=base, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    from nnstreamer_tpu.models._init import fast_init

    variables = fast_init(model.init, rng, dummy, seed=seed)

    def apply_fn(params, x):
        return model.apply(params, x)

    in_info = TensorsInfo.from_str(
        f"3:{image_size}:{image_size}:{batch}", "float32")
    out_info = TensorsInfo.from_str(
        f"{num_classes}:{image_size}:{image_size}:{batch}", "float32")
    return apply_fn, variables, in_info, out_info
