"""PoseNet — keypoint heatmap model (benchmark config #3).

The reference's pose_estimation decoder (tensordec-pose.c, 824 LoC)
consumes a PoseNet-style output: heatmaps [keypoints, W/stride, H/stride]
plus short-range offsets. This module provides that contract natively: a
small conv backbone producing 17-keypoint heatmaps + 2·17 offsets.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from nnstreamer_tpu.models.mobilenet_v2 import InvertedResidual
from nnstreamer_tpu.tensors.types import TensorsInfo

NUM_KEYPOINTS = 17


class PoseNet(nn.Module):
    num_keypoints: int = NUM_KEYPOINTS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu6(nn.BatchNorm(use_running_average=True,
                                  dtype=self.dtype)(x))
        for expand, out_ch, repeats, stride in [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 2, 2), (6, 64, 2, 2),
        ]:
            for i in range(repeats):
                x = InvertedResidual(out_ch, stride if i == 0 else 1,
                                     expand, self.dtype)(x)
        heat = nn.Conv(self.num_keypoints, (1, 1), dtype=self.dtype)(x)
        offs = nn.Conv(self.num_keypoints * 2, (1, 1), dtype=self.dtype)(x)
        return (jax.nn.sigmoid(heat).astype(jnp.float32),
                offs.astype(jnp.float32))


def posenet(image_size: int = 257, batch: int = 1, dtype=jnp.bfloat16,
            seed: int = 0) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    model = PoseNet(dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    from nnstreamer_tpu.models._init import fast_init
    variables = fast_init(model.init, rng, dummy, seed=seed)
    h, o = jax.eval_shape(lambda p, x: model.apply(p, x), variables, dummy)

    def apply_fn(params, x):
        return model.apply(params, x)

    in_info = TensorsInfo.from_str(
        f"3:{image_size}:{image_size}:{batch}", "float32")
    out_info = TensorsInfo.from_str(
        f"{h.shape[3]}:{h.shape[2]}:{h.shape[1]}:{batch},"
        f"{o.shape[3]}:{o.shape[2]}:{o.shape[1]}:{batch}",
        "float32,float32")
    return apply_fn, variables, in_info, out_info
