"""LSTM cell — the recurrent model for tensor_repo loops (benchmark
config #5; reference tests/nnstreamer_repo_lstm with a fake LSTM custom
filter).

The step function is shaped for the repo-loop pipeline: one invoke per
frame, hidden/cell state flowing through repo slots as device-resident
arrays (state never leaves HBM between iterations — SURVEY §5's
"device-resident state" requirement).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from nnstreamer_tpu.tensors.types import TensorsInfo


class LSTMCellModel(nn.Module):
    hidden: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, h, c):
        gates = nn.Dense(4 * self.hidden, dtype=self.dtype)(
            jnp.concatenate([x, h], axis=-1)
        )
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c2 = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
        return h2.astype(jnp.float32), h2.astype(jnp.float32), \
            c2.astype(jnp.float32)


def lstm_cell(input_dim: int = 128, hidden: int = 128, batch: int = 1,
              dtype=jnp.float32, seed: int = 0
              ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """Factory: apply_fn(params, x, h, c) -> (y, h', c')."""
    model = LSTMCellModel(hidden=hidden, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    zeros = (jnp.zeros((batch, input_dim)), jnp.zeros((batch, hidden)),
             jnp.zeros((batch, hidden)))
    variables = model.init(rng, *zeros)

    def apply_fn(params, x, h, c):
        return model.apply(params, x, h, c)

    in_info = TensorsInfo.from_str(
        f"{input_dim}:{batch},{hidden}:{batch},{hidden}:{batch}",
        "float32,float32,float32")
    out_info = TensorsInfo.from_str(
        f"{hidden}:{batch},{hidden}:{batch},{hidden}:{batch}",
        "float32,float32,float32")
    return apply_fn, variables, in_info, out_info
