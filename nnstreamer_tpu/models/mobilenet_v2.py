"""MobileNetV2 — the north-star classification model (benchmark config #1).

The reference benches ``mobilenet_v2_1.0_224_quant.tflite`` through its
tflite subplugin; here the same architecture (Sandler et al. 2018:
inverted residuals, linear bottlenecks) is native flax, with TPU choices:

- NHWC layout and channel counts padded to multiples of 8 so conv lowering
  tiles cleanly onto the MXU;
- optional bfloat16 activations/weights (``dtype=jnp.bfloat16``) — fp32
  accumulation is XLA's default for bf16 convs on TPU;
- no dynamic shapes anywhere; one jit specialization per batch size.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.tensors.types import TensorsInfo


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class InvertedResidual(nn.Module):
    out_ch: int
    stride: int
    expand: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        identity = x
        if self.expand != 1:
            x = nn.Conv(hidden, (1, 1), use_bias=False, dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
            x = nn.relu6(x)
        x = nn.Conv(hidden, (3, 3), strides=(self.stride, self.stride),
                    padding="SAME", feature_group_count=hidden,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        x = nn.relu6(x)
        x = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        if self.stride == 1 and in_ch == self.out_ch:
            x = x + identity
        return x


class MobileNetV2(nn.Module):
    num_classes: int = 1001
    width: float = 1.0
    dtype: Any = jnp.float32

    # (expand, out_ch, repeats, stride) — the paper's table 2
    CFG = [
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
    ]

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        ch = _make_divisible(32 * self.width)
        x = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        x = nn.relu6(x)
        for expand, out_ch, repeats, stride in self.CFG:
            out_ch = _make_divisible(out_ch * self.width)
            for i in range(repeats):
                x = InvertedResidual(
                    out_ch, stride if i == 0 else 1, expand, self.dtype
                )(x)
        last = _make_divisible(1280 * max(self.width, 1.0))
        x = nn.Conv(last, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        x = nn.relu6(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def mobilenet_v2(num_classes: int = 1001, width: float = 1.0,
                 image_size: int = 224, batch: int = 1,
                 dtype=jnp.bfloat16, seed: int = 0
                 ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """Factory: (apply_fn, params, in_info, out_info).

    Input: float32 NHWC in [0,1]·any-normalization (the pipeline's
    tensor_transform owns preprocessing, like the reference pipelines do).
    """
    model = MobileNetV2(num_classes=num_classes, width=width, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    from nnstreamer_tpu.models._init import fast_init
    variables = fast_init(model.init, rng, dummy, seed=seed)

    def apply_fn(params, x):
        return model.apply(params, x)

    in_info = TensorsInfo.from_str(
        f"3:{image_size}:{image_size}:{batch}", "float32")
    out_info = TensorsInfo.from_str(f"{num_classes}:{batch}", "float32")
    return apply_fn, variables, in_info, out_info
