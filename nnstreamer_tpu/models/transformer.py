"""Decoder-only transformer LM — the long-context / multi-chip flagship.

New capability beyond the reference (which has no attention/sequence models
in-framework, SURVEY §5): a GPT-style LM whose parameters are laid out for
SPMD sharding (see ``parallel.sharded`` for the axis rules) and whose
attention can run as **ring attention** over a sequence-parallel mesh axis
(``parallel.ring``). TPU-first choices:

- layers are **stacked** (one leading L axis per param) and applied with
  ``lax.scan`` — one compiled layer body instead of L inlined copies;
- bfloat16 activations, fp32 layernorm/softmax accumulations;
- rotary position embeddings (no learned positional table to shard);
- optional top-1 MoE FFN whose expert dim maps to the ``ep`` mesh axis.

Params are a plain pytree dict, so sharding rules are transparent
name-based PartitionSpecs rather than framework metadata.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nnstreamer_tpu.tensors.types import TensorsInfo


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    num_experts: int = 0  # 0 → dense FFN; >0 → top-1 MoE

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)

    def norm(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) > 1 else 1))
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * 0.02
        )

    L, D, H, Dh, F = (cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim,
                      cfg.d_ff)
    p = {
        "embed": norm(cfg.vocab, D),
        "ln1": jnp.ones((L, D), jnp.float32),
        "qkv": norm(L, D, 3, H, Dh),
        "proj": norm(L, H, Dh, D),
        "ln2": jnp.ones((L, D), jnp.float32),
        "ln_f": jnp.ones((D,), jnp.float32),
    }
    if cfg.num_experts:
        p["router"] = norm(L, D, cfg.num_experts)
        p["w_in"] = norm(L, cfg.num_experts, D, F)
        p["w_out"] = norm(L, cfg.num_experts, F, D)
    else:
        p["w_in"] = norm(L, D, F)
        p["w_out"] = norm(L, F, D)
    return p


def _rmsnorm(x, scale):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _rope(x, positions):
    """Rotary embeddings; x [b, s, h, d], positions [b, s]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b,s,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _dense_ffn(x, w_in, w_out, dtype):
    h = jnp.einsum("bsd,df->bsf", x, w_in.astype(dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("bsf,fd->bsd", h, w_out.astype(dtype))


def _block_qkv(x, lp, positions, dtype):
    """Pre-norm + qkv projection + rope — shared by the full forward's
    layer body and the KV-cached decode body."""
    h = _rmsnorm(x, lp["ln1"])
    qkv = jnp.einsum("bsd,dthc->btshc", h, lp["qkv"].astype(dtype))
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]            # [b,s,h,dh]
    return _rope(q, positions), _rope(k, positions), v


def _block_tail(x, a, lp, cfg):
    """Attention-output projection + residual + FFN block — shared by the
    full forward's layer body and the KV-cached decode body."""
    dtype = cfg.dtype
    x = x + jnp.einsum("bshc,hcd->bsd", a, lp["proj"].astype(dtype))
    h2 = _rmsnorm(x, lp["ln2"])
    if cfg.num_experts:
        return x + _moe_ffn(h2, lp["router"], lp["w_in"], lp["w_out"],
                            dtype)
    return x + _dense_ffn(h2, lp["w_in"], lp["w_out"], dtype)


def _attend_cache(q, ck, cv, mask, head_dim, dtype):
    """The ONE cached-attention numeric core shared by single-token decode
    and chunk decode: fp32 scores (same scale FORM as attention_reference,
    flash_attention.py:45), fp32 softmax AND fp32 probs×values, rounding
    only the final output — bit-matches the full forward so greedy
    decode/forward parity holds in bfloat16 configs too."""
    scores = jnp.einsum("bqhc,bshc->bhqs", q.astype(jnp.float32),
                        ck.astype(jnp.float32))
    scores = scores * head_dim ** -0.5
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqs,bshc->bqhc", probs,
                      cv.astype(jnp.float32)).astype(dtype)


def _final_logits(x, params):
    """Final rmsnorm + tied-embedding projection, shared by every forward
    variant so logit math can never diverge between them."""
    x = _rmsnorm(x, params["ln_f"])
    return jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                      params["embed"])


def _moe_ffn(x, router, w_in, w_out, dtype):
    """Top-1 routed MoE: expert axis shards over mesh axis ``ep`` (the
    one-hot dispatch einsum lets GSPMD all-to-all tokens to experts)."""
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32),
                        router.astype(jnp.float32))
    gate = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(gate, axis=-1)                      # [b,s]
    onehot = jax.nn.one_hot(top, router.shape[-1], dtype=dtype)  # [b,s,e]
    weight = jnp.take_along_axis(gate, top[..., None], -1)[..., 0].astype(
        dtype)                                                   # [b,s]
    h = jnp.einsum("bsd,bse,edf->bsef", x, onehot, w_in.astype(dtype))
    h = jax.nn.gelu(h)
    out = jnp.einsum("bsef,efd->bsed", h, w_out.astype(dtype))
    return jnp.sum(out * onehot[..., None], axis=2) * weight[..., None]


def make_layer_body(cfg: TransformerConfig,
                    attention_fn: Optional[Callable] = None,
                    capture_kv: bool = False) -> Callable:
    """One transformer block as a ``lax.scan`` body over stacked layer
    params: ``layer_body((x, positions), layer_params) -> ((x, positions),
    ys)``. Shared by the plain forward (scan over all L layers), the
    pipeline-parallel forward (each stage scans its local L/pp layers),
    and prefill (``capture_kv=True`` → ys is the layer's rope'd
    ``stack([k, v])`` for decode-cache seeding)."""
    from nnstreamer_tpu.parallel.ring import attention_reference

    attn = attention_fn or attention_reference
    dtype = cfg.dtype

    def layer_body(x_and_pos, lp):
        x, positions = x_and_pos
        q, k, v = _block_qkv(x, lp, positions, dtype)
        a = attn(q, k, v)                                # [b,s,h,dh]
        x = _block_tail(x, a, lp, cfg)
        return (x, positions), (jnp.stack([k, v]) if capture_kv else None)

    return layer_body


def build_forward(cfg: TransformerConfig,
                  attention_fn: Optional[Callable] = None) -> Callable:
    """Returns apply_fn(params, tokens[int32 b,s]) -> logits[b,s,vocab].

    ``attention_fn(q, k, v)`` defaults to single-device causal attention;
    pass a ring-attention closure (inside shard_map) for sequence
    parallelism. ``positions`` are offset by the sp shard index when the
    attention_fn provides ``.position_offset`` (set by the sharded step
    builder) so rotary phases stay globally correct.
    """
    dtype = cfg.dtype
    layer_body = make_layer_body(cfg, attention_fn)

    def apply_fn(params, tokens, position_offset=0):
        b, s = tokens.shape
        positions = position_offset + jnp.arange(s)[None, :].astype(
            jnp.int32
        ) * jnp.ones((b, 1), jnp.int32)
        x = params["embed"].astype(dtype)[tokens]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}
        (x, _), _ = lax.scan(layer_body, (x, positions), layer_params)
        return _final_logits(x, params)

    return apply_fn


def _slot_write(layer_cache, upd, pos, per_stream):
    """Write ``upd`` into a layer cache leaf at sequence slot(s) ``pos``.

    Leaf layout is ``[2, b, S, ...]`` (slot axis 2, any trailing rank —
    values have dh, scales don't). ``per_stream`` scatters per batch row
    with that row's own pos."""
    if per_stream:
        return jax.vmap(
            lambda cch, u, p: jax.lax.dynamic_update_slice(
                cch, u, (0, p) + (0,) * (cch.ndim - 2)),
            in_axes=(1, 1, 0), out_axes=1)(layer_cache, upd, pos)
    return jax.lax.dynamic_update_slice(
        layer_cache, upd, (0, 0, pos) + (0,) * (layer_cache.ndim - 3))


def _paged_gather(pages, bt):
    """Per-layer block gather: ``pages [NTOT, 2, T, ...]`` + block table
    ``bt [b, MB]`` → contiguous ``[b, 2, MB*T, ...]`` k/v in global-slot
    order. Table entries ≥ NTOT-1 (the pool's unallocated sentinel) clamp
    onto the pool's permanent ZERO block at index NTOT-1, so unallocated
    slots read exact zeros — finite, and masked out anyway."""
    ntot = pages.shape[0]
    g = pages[jnp.minimum(bt, ntot - 1)]                 # [b,MB,2,T,...]
    g = jnp.moveaxis(g, 2, 1)                            # [b,2,MB,T,...]
    b, two, mb, t = g.shape[:4]
    return g.reshape((b, two, mb * t) + g.shape[4:])


def _paged_scatter(pages, upd, blk, off):
    """Per-layer block scatter: ``upd [b, c, 2, ...]`` into
    ``pages[blk, :, off]`` (``blk``/``off`` are ``[b, c]``). Out-of-range
    block ids (the sentinel) DROP — a masked write, not a clamped one, so
    the zero block is never corrupted."""
    return pages.at[blk, :, off].set(upd, mode="drop")


class _RawKVCodec:
    """Cache = one array [L, 2, b, S, h, dh] in the model dtype."""

    def __init__(self, dtype):
        self.dtype = dtype

    def init(self, L, b, S, h, dh):
        return jnp.zeros((L, 2, b, S, h, dh), self.dtype)

    def write(self, layer_cache, kv, pos, per_stream=False):
        """kv [2, b, c, h, dh] → slots [pos, pos+c) (per-row pos when
        ``per_stream``)."""
        return _slot_write(layer_cache, kv.astype(self.dtype), pos,
                           per_stream)

    def read(self, layer_cache):
        return layer_cache[0], layer_cache[1]

    def place_prefix(self, cache, kv):
        """kv [L, 2, b, s, h, dh] → cache slots [0, s)."""
        return jax.lax.dynamic_update_slice(
            cache, kv.astype(self.dtype), (0, 0, 0, 0, 0, 0))

    def paged_init(self, L, ntot, T, h, dh):
        """Paged arena [L, NTOT, 2, T, h, dh] — leading L so a layer scan
        carries one block pool slice per layer (serving/kvpool.py owns
        allocation; index NTOT-1 is the permanent zero block)."""
        return jnp.zeros((L, ntot, 2, T, h, dh), self.dtype)

    def paged_write(self, pages, kv, blk, off):
        """kv [2, b, c, h, dh] → pages[blk[b,c], :, off[b,c]]."""
        upd = jnp.transpose(kv.astype(self.dtype), (1, 2, 0, 3, 4))
        return _paged_scatter(pages, upd, blk, off)

    def paged_read(self, pages, bt):
        g = _paged_gather(pages, bt)
        return g[:, 0], g[:, 1]


class _Int8KVCodec:
    """int8 KV cache: values [L, 2, b, S, h, dh] int8 + per-vector absmax
    scales [L, 2, b, S, h] fp32 — ~2× context (or batch slots) per HBM
    byte vs bf16, and the attend path reads half the bytes. Dequantize
    happens in fp32 right before the score/pv einsums, so the attention
    numeric core (_attend_cache) is unchanged."""

    def _q(self, kv):
        kf = kv.astype(jnp.float32)
        amax = jnp.max(jnp.abs(kf), axis=-1, keepdims=True)
        scale = jnp.maximum(amax / 127.0, 1e-30)
        q = jnp.clip(jnp.round(kf / scale), -127, 127).astype(jnp.int8)
        return q, scale[..., 0]

    def init(self, L, b, S, h, dh):
        return {"q": jnp.zeros((L, 2, b, S, h, dh), jnp.int8),
                "scale": jnp.zeros((L, 2, b, S, h), jnp.float32)}

    def write(self, layer_cache, kv, pos, per_stream=False):
        q, s = self._q(kv)                 # [2,b,c,h,dh], [2,b,c,h]
        return {"q": _slot_write(layer_cache["q"], q, pos, per_stream),
                "scale": _slot_write(layer_cache["scale"], s, pos,
                                     per_stream)}

    def read(self, layer_cache):
        deq = (layer_cache["q"].astype(jnp.float32)
               * layer_cache["scale"][..., None])
        return deq[0], deq[1]

    def place_prefix(self, cache, kv):
        q, s = self._q(kv)                 # [L,2,b,s,h,dh], [L,2,b,s,h]
        return {
            "q": jax.lax.dynamic_update_slice(
                cache["q"], q, (0, 0, 0, 0, 0, 0)),
            "scale": jax.lax.dynamic_update_slice(
                cache["scale"], s, (0, 0, 0, 0, 0)),
        }

    def paged_init(self, L, ntot, T, h, dh):
        return {"q": jnp.zeros((L, ntot, 2, T, h, dh), jnp.int8),
                "scale": jnp.zeros((L, ntot, 2, T, h), jnp.float32)}

    def paged_write(self, pages, kv, blk, off):
        """Codec applied per block: each written vector quantizes with the
        same per-vector absmax math as the monolithic write, so paged int8
        caches are bit-identical to monolithic int8 ones."""
        q, s = self._q(kv)                 # [2,b,c,h,dh], [2,b,c,h]
        return {
            "q": _paged_scatter(pages["q"],
                                jnp.transpose(q, (1, 2, 0, 3, 4)),
                                blk, off),
            "scale": _paged_scatter(pages["scale"],
                                    jnp.transpose(s, (1, 2, 0, 3)),
                                    blk, off),
        }

    def paged_read(self, pages, bt):
        gq = _paged_gather(pages["q"], bt)
        gs = _paged_gather(pages["scale"], bt)
        deq = gq.astype(jnp.float32) * gs[..., None]
        return deq[:, 0], deq[:, 1]


def _kv_codec(cfg: TransformerConfig, kv_codec: Optional[str]):
    if kv_codec in (None, "raw"):
        return _RawKVCodec(cfg.dtype)
    if kv_codec == "int8":
        return _Int8KVCodec()
    raise ValueError(
        f"kv_codec must be None/'raw'/'int8', got {kv_codec!r}")


def init_cache(cfg: TransformerConfig, batch: int,
               max_seq: Optional[int] = None,
               kv_codec: Optional[str] = None):
    """Device-resident KV cache [L, 2, b, S, h, dh] (k=0, v=1 slots).
    ``kv_codec="int8"`` returns the quantized layout (values + per-vector
    scales) accepted by the matching ``build_*`` functions."""
    s = max_seq or cfg.max_seq
    return _kv_codec(cfg, kv_codec).init(
        cfg.n_layers, batch, s, cfg.n_heads, cfg.head_dim)


def build_decode_step(cfg: TransformerConfig,
                      max_seq: Optional[int] = None,
                      kv_codec: Optional[str] = None) -> Callable:
    """Incremental (KV-cached) single-token decode.

    ``step(params, token[int32 b], cache, pos[int32 scalar]) ->
    (logits[b, vocab], new_cache)`` — one position's q/k/v are computed,
    k/v written into the cache at ``pos`` (``dynamic_update_slice``), and
    attention runs against the cached prefix under a ``<= pos`` mask. The
    cache is a jittable carry: it stays in HBM across steps, the streaming
    pipeline's tensor_repo loop circulating only array handles (the
    reference's LSTM repo pattern, tests/nnstreamer_repo_lstm, scaled to
    autoregressive LM decode). Jit with ``donate_argnums`` on the cache to
    update it in place.

    Cache-length contract: ``pos`` is clamped to the last cache slot — a
    step past ``max_seq`` overwrites slot S-1 and attends over the stored
    prefix (bounded degradation, never an unmasked-garbage read). Callers
    streaming longer sequences should size the cache accordingly or reset
    it.

    ``pos`` may be a scalar (all streams in lock-step) or a ``[b]``
    vector — one position per batch row, the continuous-batching shape:
    sequences at different depths decode together in one dispatch, each
    writing its own cache slot and masking its own prefix.

    ``kv_codec="int8"`` stores the cache quantized (see _Int8KVCodec);
    pass the matching ``init_cache(..., kv_codec="int8")`` cache.
    """
    dtype = cfg.dtype
    s_max = max_seq or cfg.max_seq
    codec = _kv_codec(cfg, kv_codec)

    def step(params, token, cache, pos):
        b = token.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        per_stream = pos.ndim == 1
        pos_c = jnp.minimum(pos, s_max - 1)  # see cache-length contract
        x = params["embed"].astype(dtype)[token][:, None]       # [b,1,d]
        positions = pos[:, None] if per_stream \
            else jnp.full((b, 1), pos, jnp.int32)
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}

        def layer(carry, lp_and_cache):
            x, = carry
            lp, layer_cache = lp_and_cache                # [2,b,S,h,dh]
            q, k, v = _block_qkv(x, lp, positions, dtype)  # [b,1,h,dh]
            new_cache = codec.write(layer_cache, jnp.stack([k, v]),
                                    pos_c, per_stream)
            slots = jnp.arange(s_max)
            mask = slots[None, None, None, :] <= (
                pos_c[:, None, None, None] if per_stream else pos_c)
            ck, cv = codec.read(new_cache)
            a = _attend_cache(q, ck, cv, mask, cfg.head_dim, dtype)
            x = _block_tail(x, a, lp, cfg)
            return (x,), new_cache

        (x,), new_cache = lax.scan(layer, (x,), (layer_params, cache))
        return _final_logits(x, params)[:, 0], new_cache

    return step


def build_chunk_decode(cfg: TransformerConfig,
                       max_seq: Optional[int] = None,
                       kv_codec: Optional[str] = None) -> Callable:
    """KV-cached decode of a WHOLE chunk of c tokens in one pass:
    ``chunk(params, tokens[int32 b,c], cache, pos0[int32 scalar]) ->
    (logits[b,c,vocab], new_cache)``.

    Generalizes :func:`build_decode_step` (c=1) to the shape speculative
    verification needs (models/speculative.py): the target model scores c
    candidate positions in ONE program — a [c, d_model] matmul per layer
    instead of c sequential single-row dispatches, which is exactly what
    the MXU wants. Position ``pos0+i`` writes cache slot ``pos0+i`` and
    attends under a ``slot <= pos0+i`` mask (write-before-attend, so
    stale kv beyond an accepted prefix is unreachable — the rewind-free
    speculative cache contract; see speculative.py docstring).

    ``pos0`` is clamped so the chunk's writes stay inside the cache
    (same bounded-degradation contract as build_decode_step).

    Like build_decode_step, ``pos0`` may also be a ``[b]`` vector — one
    chunk origin per batch row (the batched speculative-verify shape:
    every stream scores its own γ+1 candidates at its own depth in ONE
    program). The scalar path traces exactly as before.
    """
    dtype = cfg.dtype
    s_max = max_seq or cfg.max_seq
    codec = _kv_codec(cfg, kv_codec)

    def chunk(params, tokens, cache, pos0):
        b, c = tokens.shape
        pos0 = jnp.asarray(pos0, jnp.int32)
        per_stream = pos0.ndim == 1
        pos0 = jnp.minimum(pos0, s_max - c)
        if per_stream:
            positions = pos0[:, None] + jnp.arange(c)[None, :]   # [b,c]
            # query i of row r (global position pos0[r]+i) sees
            # slots <= pos0[r]+i
            qpos = positions[:, None, :, None]                # [b,1,c,1]
        else:
            positions = pos0 + jnp.arange(c)[None, :] * jnp.ones(
                (b, 1), jnp.int32)                               # [b,c]
            # query i (global position pos0+i) sees slots <= pos0+i
            qpos = (pos0 + jnp.arange(c))[None, None, :, None]
        x = params["embed"].astype(dtype)[tokens]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}

        def layer(carry, lp_and_cache):
            x, = carry
            lp, layer_cache = lp_and_cache
            q, k, v = _block_qkv(x, lp, positions, dtype)  # [b,c,h,dh]
            new_cache = codec.write(layer_cache, jnp.stack([k, v]), pos0,
                                    per_stream)
            slots = jnp.arange(s_max)
            mask = slots[None, None, None, :] <= qpos
            ck, cv = codec.read(new_cache)
            a = _attend_cache(q, ck, cv, mask, cfg.head_dim, dtype)
            x = _block_tail(x, a, lp, cfg)
            return (x,), new_cache

        (x,), new_cache = lax.scan(layer, (x,), (layer_params, cache))
        return _final_logits(x, params), new_cache

    return chunk


def build_paged_decode_step(cfg: TransformerConfig,
                            block_tokens: int,
                            max_seq: Optional[int] = None,
                            kv_codec: Optional[str] = None) -> Callable:
    """Single-token decode against a PAGED KV cache (serving/kvpool.py):
    ``step(params, token[int32 b], arena, bt[int32 b,MB], pos[int32 b]) ->
    (logits[b, vocab], new_arena)``.

    The arena is the pool's ``[L, NTOT, 2, T, h, dh]`` pytree; ``bt`` maps
    each row's logical blocks ``0..MB-1`` (MB = S/T) to physical pool
    blocks, with unallocated entries holding the pool sentinel (≥ NTOT).
    Each step scatters k/v into physical slot ``(bt[pos//T], pos%T)`` and
    gathers the row's table back into the contiguous ``[b, S, ...]``
    layout the shared attention core expects — same slot ordering, same
    write-before-attend discipline, and masked slots contribute EXACT
    zeros (−1e30 scores underflow softmax to 0.0), so greedy outputs are
    bit-identical to the monolithic cache. Rows whose table is all
    sentinel (empty batch lanes) drop their writes and read the zero
    block — inert by construction.
    """
    dtype = cfg.dtype
    s_max = max_seq or cfg.max_seq
    T = int(block_tokens)
    if T <= 0 or s_max % T:
        raise ValueError(
            f"build_paged_decode_step: max_seq ({s_max}) must be a "
            f"positive multiple of block_tokens ({block_tokens})")
    codec = _kv_codec(cfg, kv_codec)

    def step(params, token, arena, bt, pos):
        pos = jnp.asarray(pos, jnp.int32)
        pos_c = jnp.minimum(pos, s_max - 1)  # cache-length contract
        x = params["embed"].astype(dtype)[token][:, None]       # [b,1,d]
        positions = pos[:, None]
        blk = jnp.take_along_axis(bt, (pos_c // T)[:, None], axis=1)
        off = (pos_c % T)[:, None]                               # [b,1]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}

        def layer(carry, lp_and_pages):
            x, = carry
            lp, pages = lp_and_pages              # one layer's blocks
            q, k, v = _block_qkv(x, lp, positions, dtype)  # [b,1,h,dh]
            pages = codec.paged_write(pages, jnp.stack([k, v]), blk, off)
            slots = jnp.arange(s_max)
            mask = slots[None, None, None, :] <= pos_c[:, None, None,
                                                       None]
            ck, cv = codec.paged_read(pages, bt)
            a = _attend_cache(q, ck, cv, mask, cfg.head_dim, dtype)
            x = _block_tail(x, a, lp, cfg)
            return (x,), pages

        (x,), new_arena = lax.scan(layer, (x,), (layer_params, arena))
        return _final_logits(x, params)[:, 0], new_arena

    return step


def build_paged_chunk(cfg: TransformerConfig,
                      block_tokens: int,
                      max_seq: Optional[int] = None,
                      kv_codec: Optional[str] = None) -> Callable:
    """Chunk decode against a paged KV cache — build_chunk_decode's paged
    twin: ``chunk(params, tokens[int32 b,c], arena, bt[int32 b,MB],
    pos0[int32 b], limit[int32 b]) -> (logits[b,c,vocab], new_arena)``.

    Row r's token i sits at global position ``pos0[r]+i``, writes physical
    slot ``(bt[r, p//T], p%T)`` and attends under a ``slot <= p`` mask.
    ``limit[r]`` is the row's REAL chunk length: positions ≥ limit (bucket
    padding) redirect their writes to the sentinel and drop, so a padded
    warm prefix extension never smears pad k/v into pool blocks another
    stream could inherit. Used for prefix-cache extension and speculative
    verification on the paged path.
    """
    dtype = cfg.dtype
    s_max = max_seq or cfg.max_seq
    T = int(block_tokens)
    if T <= 0 or s_max % T:
        raise ValueError(
            f"build_paged_chunk: max_seq ({s_max}) must be a positive "
            f"multiple of block_tokens ({block_tokens})")
    codec = _kv_codec(cfg, kv_codec)

    def chunk(params, tokens, arena, bt, pos0, limit):
        b, c = tokens.shape
        pos0 = jnp.minimum(jnp.asarray(pos0, jnp.int32), s_max - c)
        positions = pos0[:, None] + jnp.arange(c)[None, :]       # [b,c]
        valid = jnp.arange(c)[None, :] < jnp.asarray(
            limit, jnp.int32)[:, None]
        ntot = jax.tree_util.tree_leaves(arena)[0].shape[1]
        blk = jnp.take_along_axis(bt, positions // T, axis=1)    # [b,c]
        blk = jnp.where(valid, blk, jnp.int32(ntot))   # pad writes drop
        off = positions % T
        x = params["embed"].astype(dtype)[tokens]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}

        def layer(carry, lp_and_pages):
            x, = carry
            lp, pages = lp_and_pages
            q, k, v = _block_qkv(x, lp, positions, dtype)  # [b,c,h,dh]
            pages = codec.paged_write(pages, jnp.stack([k, v]), blk, off)
            slots = jnp.arange(s_max)
            mask = slots[None, None, None, :] <= positions[:, None, :,
                                                           None]
            ck, cv = codec.paged_read(pages, bt)
            a = _attend_cache(q, ck, cv, mask, cfg.head_dim, dtype)
            x = _block_tail(x, a, lp, cfg)
            return (x,), pages

        (x,), new_arena = lax.scan(layer, (x,), (layer_params, arena))
        return _final_logits(x, params), new_arena

    return chunk


def build_prefill(cfg: TransformerConfig,
                  max_seq: Optional[int] = None,
                  attention_fn: Optional[Callable] = None,
                  kv_codec: Optional[str] = None) -> Callable:
    """Prompt ingestion for streaming decode: ``prefill(params,
    tokens[int32 b,s]) -> (logits[b, vocab], cache)`` — one full-sequence
    forward (the SAME shared layer body as :func:`build_forward`, with
    k/v captured) that seeds a fresh decode cache, so generation continues
    from ``pos = s`` with :func:`build_decode_step`. The last position's
    logits seed the first sampled token. ``attention_fn`` plugs in a flash
    kernel for the O(s²) prompt pass exactly as in build_forward.

    ``prefill(params, tokens, lengths)`` with ``lengths[int32 b]`` supports
    RIGHT-PADDED prompts (bucketed compile shapes, serving.engine): logits
    are taken at each row's true last position ``lengths-1``. Trailing-pad
    kv entries land in cache slots ≥ length; they are garbage but
    unreachable — decode's ``slots <= pos`` mask only admits slot i once
    pos reaches i, and the decode step WRITES slot i (overwriting the pad
    kv) before attending on that same step, so a padded prefill is
    bit-identical to an exact-length one for all future tokens."""
    dtype = cfg.dtype
    s_max = max_seq or cfg.max_seq
    codec = _kv_codec(cfg, kv_codec)
    layer_body = make_layer_body(cfg, attention_fn, capture_kv=True)

    def prefill(params, tokens, lengths=None):
        b, s = tokens.shape
        positions = jnp.arange(s)[None, :].astype(jnp.int32) * jnp.ones(
            (b, 1), jnp.int32)
        x = params["embed"].astype(dtype)[tokens]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}
        (x, _), kv = lax.scan(layer_body, (x, positions), layer_params)
        # park each layer's k/v ([L,2,b,s,h,dh]) in the first s cache slots
        cache = codec.place_prefix(
            codec.init(cfg.n_layers, b, s_max, cfg.n_heads, cfg.head_dim),
            kv)
        x = _rmsnorm(x, params["ln_f"])
        if lengths is None:
            last = x[:, -1]
        else:
            idx = (jnp.asarray(lengths, jnp.int32) - 1)[:, None, None]
            last = jnp.take_along_axis(
                x, jnp.broadcast_to(idx, (b, 1, x.shape[-1])), axis=1
            )[:, 0]
        logits = jnp.einsum("bd,vd->bv", last.astype(jnp.float32),
                            params["embed"])
        return logits, cache

    return prefill


def build_greedy_stream_step(cfg: TransformerConfig,
                             max_seq: Optional[int] = None,
                             kv_codec: Optional[str] = None,
                             steps: int = 1) -> Callable:
    """Pipeline-shaped greedy decode step for the tensor_repo loop:
    ``step(params, token, cache, pos) -> (next_token, cache, pos+steps)``
    — the state tuple a repo slot circulates (examples/llm_stream.py,
    bench config ``decode``).

    With ``steps > 1`` the step runs a ``lax.scan`` of that many decode
    steps inside ONE program and returns a fourth output, the ``[steps]``
    token block — the serving engine's multi-step-dispatch idea applied
    to the repo loop (per-invoke dispatch overhead amortizes over the
    block; the sequential token chain itself cannot be batched). Use
    ``input-combination=i0,i1,i2`` on the filter so the circulating state
    stays (token, cache, pos)."""
    decode = build_decode_step(cfg, max_seq, kv_codec)

    def one(params, token, cache, pos):
        logits, cache2 = decode(params, token.reshape(1).astype(jnp.int32),
                                cache, pos.reshape(()).astype(jnp.int32))
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return nxt, cache2, pos + 1

    if steps <= 1:
        return one

    def step(params, token, cache, pos):
        def body(carry, _):
            tok, cache, pos = carry
            nxt, cache, pos = one(params, tok, cache, pos)
            return (nxt, cache, pos), nxt.reshape(())

        (tok, cache, pos), toks = jax.lax.scan(
            body, (token.reshape(1).astype(jnp.int32), cache,
                   pos.reshape(()).astype(jnp.int32)),
            None, length=steps)
        return tok, cache, pos, toks

    return step


def make_sampler(vocab: int, temperature: float = 1.0,
                 top_k: int = 0, min_p: float = 0.0,
                 with_logprobs: bool = False) -> Callable:
    """The ONE sampling function: ``sample(logits[n, vocab],
    keys[uint32 n, 2]) -> (tokens[int32 n], new_keys[n, 2])`` — rows draw
    independently with their own threefry key, so results never depend on
    which other rows share the batch. ``temperature<=0`` degrades to
    greedy (keys pass through untouched); ``top_k>0`` restricts sampling
    to the k highest logits; ``min_p>0`` drops tokens whose probability
    is below ``min_p`` × the top token's (the modern min-p truncation —
    adaptive where top-k is fixed; both may combine). Shared by the
    repo-loop sampled step and the serving engine so their sampling math
    can never diverge.

    ``with_logprobs=True`` appends ``logprobs[float32 n]`` — the chosen
    token's log-probability under the UNMODIFIED distribution (fp32
    log_softmax of the raw logits; temperature/top-k shape the draw, the
    report stays the model's own confidence, the convention LM serving
    APIs use)."""
    if not 0.0 <= min_p <= 1.0:
        raise ValueError(
            f"make_sampler: min_p must be in [0, 1], got {min_p} "
            f"(it is a probability RATIO vs the top token, not a count "
            f"or percentage)")

    def sample(logits, keys):
        if temperature <= 0.0:
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            new_keys = keys
        else:
            scaled = logits / temperature
            if top_k > 0:
                k = min(top_k, vocab)  # over-asking = "no restriction"
                kth = jax.lax.top_k(scaled, k)[0][:, -1:]
                scaled = jnp.where(scaled >= kth, scaled, -1e30)
            if min_p > 0.0:
                # p_i >= min_p * p_max  ⟺  s_i >= s_max + log(min_p)
                # (on the temperature-scaled logits, after top-k)
                smax = jnp.max(scaled, axis=-1, keepdims=True)
                scaled = jnp.where(
                    scaled >= smax + np.log(min_p), scaled, -1e30)

            def row(key_row, logit_row):
                kk = jax.random.wrap_key_data(
                    jnp.asarray(key_row, jnp.uint32), impl="threefry2x32")
                kk, sub = jax.random.split(kk)
                tok = jax.random.categorical(sub, logit_row)
                return jax.random.key_data(kk), tok

            new_keys, toks = jax.vmap(row)(keys, scaled)
            toks = toks.astype(jnp.int32)
        if not with_logprobs:
            return toks, new_keys
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        chosen = jnp.take_along_axis(logp, toks[:, None], axis=1)[:, 0]
        return toks, new_keys, chosen

    return sample


def build_sample_stream_step(cfg: TransformerConfig,
                             max_seq: Optional[int] = None,
                             temperature: float = 1.0,
                             top_k: int = 0, min_p: float = 0.0,
                             kv_codec: Optional[str] = None) -> Callable:
    """Sampled decode step for the repo loop: ``step(params, token, cache,
    pos, key[uint32 2]) -> (next_token, cache, pos+1, next_key)`` — the
    PRNG key rides the state tuple like the cache does, so streaming stays
    deterministic given the seed. Sampling math is :func:`make_sampler`
    with one row."""
    decode = build_decode_step(cfg, max_seq, kv_codec)
    sample = make_sampler(cfg.vocab, temperature, top_k, min_p)

    def step(params, token, cache, pos, key):
        logits, cache2 = decode(params, token.reshape(1).astype(jnp.int32),
                                cache, pos.reshape(()).astype(jnp.int32))
        nxt, keys = sample(logits,
                           jnp.asarray(key, jnp.uint32).reshape(1, 2))
        return nxt, cache2, pos + 1, keys.reshape(2)

    return step


def transformer_lm(vocab: int = 32000, d_model: int = 512, n_heads: int = 8,
                   n_layers: int = 4, d_ff: int = 2048, seq: int = 256,
                   batch: int = 1, dtype=jnp.bfloat16, num_experts: int = 0,
                   seed: int = 0, attention: str = "auto"
                   ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    """Filter-backend factory (single-device attention path).

    ``attention``: "auto" uses the Pallas flash kernel on TPU for tileable
    shapes (ops/flash_attention.py) and XLA attention elsewhere;
    "reference" forces XLA.
    """
    cfg = TransformerConfig(vocab=vocab, d_model=d_model, n_heads=n_heads,
                            n_layers=n_layers, d_ff=d_ff, dtype=dtype,
                            num_experts=num_experts)
    if attention not in ("auto", "reference"):
        raise ValueError(
            f"transformer_lm: attention must be 'auto' or 'reference', "
            f"got {attention!r}")
    params = init_params(cfg, seed)
    attention_fn = None
    if attention == "auto":
        from nnstreamer_tpu.ops import flash_attention

        attention_fn = lambda q, k, v: flash_attention(q, k, v, causal=True)
    fwd = build_forward(cfg, attention_fn)

    def apply_fn(params, tokens):
        return fwd(params, tokens)

    in_info = TensorsInfo.from_str(f"{seq}:{batch}", "int32")
    out_info = TensorsInfo.from_str(f"{vocab}:{seq}:{batch}", "float32")
    return apply_fn, params, in_info, out_info
