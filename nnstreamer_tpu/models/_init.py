"""Fast shape-based parameter initialization.

``flax.linen.Module.init`` executes the un-jitted forward pass op-by-op to
produce the variable tree — ~34 s for MobileNetV2 on a 1-core CPU host and
a full extra trace+execute on TPU. The models here are benchmark/zoo models
whose weights are random anyway (the reference ships no weights in-tree
either; its test models are external .tflite files), so we only need the
*structure*: trace abstractly with ``jax.eval_shape`` (no compile, no
execute) and materialize each leaf host-side with numpy.

Leaves are filled deterministically from the seed + leaf path:
- ``batch_stats``/``mean`` → zeros, ``var`` → ones
- ``scale`` (LayerNorm/BatchNorm gamma) → ones
- ``bias`` → zeros
- kernels/embeddings → truncated-normal-ish N(0, 1/sqrt(fan_in))

This mirrors what the standard flax initializers (lecun_normal, zeros,
ones) produce in distribution, at ~1000x the speed.
"""

from __future__ import annotations

import zlib
from typing import Any

import jax
import numpy as np


def _fill(path: str, shape, dtype, rng: np.random.Generator) -> np.ndarray:
    leaf = path.rsplit("/", 1)[-1].lower()
    if leaf == "mean":
        return np.zeros(shape, dtype)
    if leaf == "var":
        return np.ones(shape, dtype)
    if leaf in ("scale", "gamma"):
        return np.ones(shape, dtype)
    if leaf in ("bias", "beta") or not shape:
        return np.zeros(shape, dtype)
    # kernel / embedding: fan_in = product of all dims but the last
    fan_in = 1
    for d in shape[:-1]:
        fan_in *= d
    std = 1.0 / np.sqrt(max(fan_in, 1))
    return rng.normal(0.0, std, size=shape).astype(dtype)


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))
        parts.append(str(key))
    return "/".join(parts)


def fast_init(init_fn, *args, seed: int = 0, **kwargs) -> Any:
    """Drop-in for ``model.init(rng, *inputs)``: same tree, numpy-filled.

    ``init_fn`` is the bound ``model.init``; ``args`` are its arguments
    (rng first, then dummy inputs). Runs ``jax.eval_shape`` (abstract — no
    FLOPs) and fills each leaf deterministically from ``seed`` + leaf path.
    """
    shapes = jax.eval_shape(init_fn, *args, **kwargs)

    def make(path, leaf):
        p = _path_str(path)
        # independent stream per leaf, keyed by a stable (unsalted) hash of
        # the path so the same seed gives identical weights on every
        # process/host — python's hash() is salted per-process
        rng = np.random.default_rng([seed, zlib.crc32(p.encode())])
        return jax.numpy.asarray(
            _fill(p, leaf.shape, leaf.dtype, rng)
        )

    return jax.tree_util.tree_map_with_path(make, shapes)
