"""Beam search — width-W decoding as one jitted program.

New capability beyond the reference (no LM machinery in-tree). TPU-first
shape: the W beams ARE the batch — every step decodes all beams in one
KV-cached dispatch (models/transformer.build_decode_step), scores
combine in fp32, and the top-W reselection's beam reordering is a gather
on the cache's batch axis — no host round trips until the final
sequences materialize.

Length handling: beams that emit ``eos_id`` freeze (their only
continuation is another EOS at zero cost), so finished hypotheses
compete with live ones under plain summed-logprob scoring. The whole
search — expand, scan of decode/reselect steps, final sort — runs under
``lax`` control flow; one executable per (beam_width, max_new) pair.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    build_decode_step,
    build_prefill,
)

_NEG = -1e30


def build_beam_search(cfg: TransformerConfig, beam_width: int = 4,
                      max_new: int = 32,
                      max_seq: Optional[int] = None,
                      eos_id: Optional[int] = None):
    """Returns ``search(params, prompt[int32 1, n]) ->
    (sequences[int32 W, max_new], scores[float32 W])``, best beam first.

    Scores are summed fp32 log-probabilities of the emitted tokens
    (verifiable by teacher-forced re-scoring — tested). A beam that
    emits ``eos_id`` is finished: its sequence pads with EOS and its
    score freezes.
    """
    if not 0 < beam_width <= cfg.vocab:
        raise ValueError(f"beam_search: beam_width must be in (0, "
                         f"{cfg.vocab}], got {beam_width}")
    if max_new < 1:
        raise ValueError(f"beam_search: max_new must be >= 1, got "
                         f"{max_new}")
    W = int(beam_width)
    s_max = max_seq or cfg.max_seq
    prefill = build_prefill(cfg, s_max)
    decode = build_decode_step(cfg, s_max)

    def search(params, prompt):
        n = prompt.shape[1]
        logits, cache1 = prefill(params, prompt)         # [1,V], slot-n
        logp0 = jax.nn.log_softmax(logits[0].astype(jnp.float32))
        scores, toks0 = jax.lax.top_k(logp0, W)          # [W], [W]
        # beams as batch: tile the prompt cache to W rows
        cache = jax.tree.map(
            lambda a: jnp.broadcast_to(
                a, a.shape[:2] + (W,) + a.shape[3:]), cache1)
        seqs = jnp.zeros((W, max_new), jnp.int32)
        seqs = seqs.at[:, 0].set(toks0)
        done = (jnp.zeros((W,), bool) if eos_id is None
                else toks0 == eos_id)

        def step(carry, m):
            seqs, scores, done, cache, last, pos = carry
            logits, cache = decode(params, last, cache, pos)   # [W,V]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            if eos_id is not None:
                # finished beams: only EOS continues, at zero cost, so
                # the frozen hypothesis competes under its final score
                eos_row = jnp.full((cfg.vocab,), _NEG).at[eos_id].set(0.0)
                logp = jnp.where(done[:, None], eos_row[None, :], logp)
            total = scores[:, None] + logp                     # [W,V]
            flat_scores, flat_idx = jax.lax.top_k(
                total.reshape(-1), W)
            parents = flat_idx // cfg.vocab                    # [W]
            toks = (flat_idx % cfg.vocab).astype(jnp.int32)
            # beam reordering = gather on the cache batch axis (axis 2
            # in every leaf: values AND int8 scales)
            cache = jax.tree.map(lambda a: a[:, :, parents], cache)
            seqs = seqs[parents].at[:, m].set(toks)
            done = done[parents]
            if eos_id is not None:
                done = jnp.logical_or(done, toks == eos_id)
            return (seqs, flat_scores, done, cache, toks, pos + 1), None

        last = toks0
        pos = jnp.full((W,), n, jnp.int32)  # per-stream positions
        (seqs, scores, done, cache, last, pos), _ = jax.lax.scan(
            step, (seqs, scores, done, cache, last, pos),
            jnp.arange(1, max_new))
        order = jnp.argsort(-scores)
        return seqs[order], scores[order]

    return search


class BeamSearcher:
    """Host-side convenience around the jitted search program."""

    def __init__(self, cfg: TransformerConfig, params: Any,
                 beam_width: int = 4, max_new: int = 32,
                 max_seq: Optional[int] = None,
                 eos_id: Optional[int] = None):
        self.cfg = cfg
        self.params = params
        self.max_new = int(max_new)
        self.S = int(max_seq or cfg.max_seq)
        self._search = jax.jit(build_beam_search(
            cfg, beam_width, max_new, self.S, eos_id))
        self.eos_id = eos_id

    def search(self, prompt) -> Tuple[np.ndarray, np.ndarray]:
        """(sequences [W, max_new], scores [W]) — best first. Sequences
        of finished beams pad with ``eos_id`` after their EOS."""
        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        # decode steps 1..max_new-1 write slots n..n+max_new-2; the last
        # must fit slot S-1
        limit = self.S - self.max_new + 1
        if not 0 < prompt.shape[1] <= limit:
            raise ValueError(
                f"beam_search: prompt length {prompt.shape[1]} must be in "
                f"(0, {limit}] so every step's cache write fits")
        seqs, scores = self._search(self.params, jnp.asarray(prompt))
        return np.asarray(seqs), np.asarray(scores)
