"""Model zoo — TPU-native networks for the benchmark configs.

The reference ships no models (they are external .tflite files under
tests/test_models); its benchmark pipelines use MobileNetV2 classification,
SSD-MobileNet detection, PoseNet, and LSTM recurrence (BASELINE.md). This
package provides those families natively in flax/JAX so the jax filter
backend serves them on TPU, plus a decoder-only transformer exercising the
long-context / multi-chip parallel paths.

Each factory returns ``(apply_fn, params, in_info, out_info)`` where
``apply_fn(params, *inputs)`` is jittable — exactly what
``filters.jax_backend`` consumes (also via ``custom=module:<factory>`` for
.msgpack checkpoints).
"""

from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2  # noqa: F401
from nnstreamer_tpu.models.ssd_mobilenet import ssd_mobilenet  # noqa: F401
from nnstreamer_tpu.models.posenet import posenet  # noqa: F401
from nnstreamer_tpu.models.lstm import lstm_cell  # noqa: F401
from nnstreamer_tpu.models.transformer import transformer_lm  # noqa: F401
from nnstreamer_tpu.models.yolo import yolo_detector  # noqa: F401
from nnstreamer_tpu.models.segmenter import segmenter  # noqa: F401
from nnstreamer_tpu.models.beam import BeamSearcher  # noqa: F401
