"""Speculative decoding — draft-and-verify generation in one program.

New capability beyond the reference (no LM machinery in-tree; its closest
idea is pipelined stages hiding latency behind throughput). On a TPU the
single-token decode step is dispatch- and bandwidth-bound: each step is a
[1, d_model]×weights pass that leaves the MXU idle. Speculative decoding
converts γ sequential target-model steps into

  1. γ cheap draft-model steps (``lax.scan`` inside the program), then
  2. ONE target-model *chunk* pass over the γ+1 candidate positions
     (``build_chunk_decode`` — a [γ+1, d_model] matmul per layer), then
  3. a vectorized accept/reject — no Python control flow.

Greedy acceptance: the emitted stream is IDENTICAL to target-only greedy
decode (tested token-for-token in tests/test_speculative.py); speculation
changes the schedule, never the output.

**Rewind-free cache contract.** A rejected suffix needs no cache
cleanup: both models write slot i before any query attends it (the
``slot <= pos`` mask admits slot i only once pos reaches i, and the
write happens earlier in the same step), so stale kv beyond the accepted
prefix is unreachable and is overwritten when generation gets there.
Resetting ``pos`` to the accept point IS the rewind.

The whole round — draft loop, verify, accept — is one jitted function
with both caches donated; the host only reads the [γ+1] emitted-token
row and the accept count.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    build_chunk_decode,
    build_decode_step,
    build_prefill,
    init_cache,
)


def build_speculative_round(target_cfg: TransformerConfig,
                            draft_cfg: TransformerConfig,
                            gamma: int = 4,
                            max_seq: Optional[int] = None) -> Callable:
    """Returns ``round(target_params, draft_params, last_tok[int32 b],
    target_cache, draft_cache, pos[int32 scalar]) -> (tokens[b, γ+1],
    n_emit[int32 scalar], target_cache, draft_cache, new_pos)``.

    ``tokens[:, :n_emit]`` are the round's emitted ids (greedy-exact
    w.r.t. the target model); ``n_emit`` ∈ [1, γ+1] — γ accepted drafts
    plus the target's bonus token, or the accepted prefix plus the
    target's correction. Entries past ``n_emit`` are the speculative
    garbage the caller must ignore.

    Batch must be 1 (checked at trace time): the accept decision is a
    single prefix length, and rows with different acceptance would need
    per-row positions through the chunk verify. Run independent
    SpeculativeDecoder instances (or the serving engine) for parallel
    streams.

    Vocabularies must match; the draft is typically 4-10x smaller.
    """
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"speculative: target vocab {target_cfg.vocab} != draft vocab "
            f"{draft_cfg.vocab}")
    if gamma < 1:
        raise ValueError(f"speculative: gamma must be >= 1, got {gamma}")
    s_max = max_seq or target_cfg.max_seq
    draft_step = build_decode_step(draft_cfg, s_max)
    target_chunk = build_chunk_decode(target_cfg, s_max)

    def spec_round(target_params, draft_params, last_tok, target_cache,
                   draft_cache, pos):
        if last_tok.shape[0] != 1:
            raise ValueError(
                f"speculative: batch must be 1 (got {last_tok.shape[0]}) "
                "— the accept prefix is a single length; run one decoder "
                "per stream")
        pos = jnp.asarray(pos, jnp.int32)

        def draft_body(carry, _):
            tok, cache, dpos = carry
            logits, cache = draft_step(draft_params, tok, cache, dpos)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, cache, dpos + 1), nxt

        (d_last, draft_cache, d_pos), drafts = jax.lax.scan(
            draft_body, (last_tok, draft_cache, pos), None, length=gamma)
        drafts = jnp.transpose(drafts)                     # [b, γ]
        # the scan wrote kv for [last, d_1..d_{γ-1}] at slots pos..pos+γ-1
        # but NOT d_γ's: on full acceptance the next round starts past
        # slot pos+γ, whose kv must be d_γ's — one extra cache-write step
        # (logits discarded) closes the hole
        _, draft_cache = draft_step(draft_params, d_last, draft_cache,
                                    d_pos)

        # target scores positions pos..pos+γ in one chunk pass over
        # [last_tok, d_1..d_γ]; logits[:, i] predicts position pos+i+1
        chunk_toks = jnp.concatenate([last_tok[:, None], drafts], axis=1)
        logits, target_cache = target_chunk(
            target_params, chunk_toks, target_cache, pos)
        target_toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # longest prefix where every draft matches the target's choice
        # (b == 1, enforced above)
        match = drafts[0] == target_toks[0, :gamma]        # [γ]
        n_acc = jnp.argmin(jnp.concatenate(
            [match, jnp.asarray([False])]).astype(jnp.int32))
        # emitted: d_1..d_n  then the target token at position n (the
        # correction on mismatch, the bonus token on full acceptance)
        out = jnp.where(jnp.arange(gamma + 1) < n_acc,
                        jnp.concatenate(
                            [drafts, drafts[:, -1:]], axis=1),
                        jnp.take_along_axis(
                            target_toks,
                            jnp.minimum(n_acc, gamma)[None, None] *
                            jnp.ones((drafts.shape[0], gamma + 1),
                                     jnp.int32),
                            axis=1))
        n_emit = n_acc + 1
        return out, n_emit, target_cache, draft_cache, pos + n_emit

    return spec_round


def build_speculative_dispatch(target_cfg: TransformerConfig,
                               draft_cfg: TransformerConfig,
                               gamma: int = 4,
                               rounds: int = 8,
                               max_seq: Optional[int] = None) -> Callable:
    """R speculative rounds in ONE program: ``dispatch(tp, dp,
    last_tok[b], t_cache, d_cache, pos) -> (buf[b, R*(γ+1)],
    n_emits[R], last_tok, t_cache, d_cache, pos)``.

    Emitted tokens append into a device-side buffer (each round's
    ``dynamic_update_slice`` at the running count overwrites the previous
    round's speculative tail), so the host pays ONE sync per R rounds —
    on a tunneled chip the per-round host round-trip dominates
    single-round speculation, exactly like the serving engine's [B, K]
    block dispatch (serving/engine.py). ``buf[:, :sum(n_emits)]`` is
    valid; a round that would write past the cache window is skipped
    (``lax.cond``) and reports ``n_emit = 0``.
    """
    spec_round = build_speculative_round(target_cfg, draft_cfg, gamma,
                                         max_seq)
    s_max = max_seq or target_cfg.max_seq
    width = gamma + 1

    def dispatch(target_params, draft_params, last_tok, t_cache, d_cache,
                 pos):
        b = last_tok.shape[0]
        buf = jnp.zeros((b, rounds * width), jnp.int32)

        def body(carry, _):
            last, t_cache, d_cache, pos, buf, count = carry

            def run(op):
                last, t_cache, d_cache, pos, buf, count = op
                toks, n_emit, t_cache, d_cache, pos = spec_round(
                    target_params, draft_params, last, t_cache, d_cache,
                    pos)
                buf = jax.lax.dynamic_update_slice(buf, toks, (0, count))
                last = jnp.take_along_axis(
                    toks, (n_emit - 1) * jnp.ones((b, 1), jnp.int32),
                    axis=1)[:, 0]
                return (last, t_cache, d_cache, pos, buf,
                        count + n_emit), n_emit

            def skip(op):
                return op, jnp.asarray(0, jnp.int32)

            carry, n_emit = jax.lax.cond(
                pos + gamma < s_max, run, skip,
                (last, t_cache, d_cache, pos, buf, count))
            return carry, n_emit

        (last_tok, t_cache, d_cache, pos, buf, _), n_emits = jax.lax.scan(
            body,
            (last_tok, t_cache, d_cache, pos, buf,
             jnp.asarray(0, jnp.int32)),
            None, length=rounds)
        return buf, n_emits, last_tok, t_cache, d_cache, pos

    return dispatch


def build_speculative_generate(target_cfg: TransformerConfig,
                               draft_cfg: TransformerConfig,
                               gamma: int,
                               max_new: int,
                               max_seq: Optional[int] = None) -> Callable:
    """A WHOLE greedy generation as one program: ``gen(tp, dp,
    last_tok[b], t_cache, d_cache, pos) -> (buf[b, max_new+γ], count)``.

    ``lax.while_loop`` drives speculative rounds until ``count >=
    max_new`` or the cache window ends — the host pays ONE sync for the
    entire generation, matching the fully-async profile of the repo-loop
    decode pipeline (bench ``decode``). ``buf[:, :min(count, max_new)]``
    is the output; the returned ``count`` is packed as
    ``[count, rounds]`` so acceptance stats survive the fusion. One
    executable per distinct ``max_new``.
    """
    spec_round = build_speculative_round(target_cfg, draft_cfg, gamma,
                                         max_seq)
    s_max = max_seq or target_cfg.max_seq
    width = max_new + gamma  # last round may overshoot by ≤ γ

    def gen(target_params, draft_params, last_tok, t_cache, d_cache, pos):
        b = last_tok.shape[0]
        buf = jnp.zeros((b, width), jnp.int32)

        def cond(carry):
            _, _, _, pos, _, count, _ = carry
            return jnp.logical_and(count < max_new, pos + gamma < s_max)

        def body(carry):
            last, t_cache, d_cache, pos, buf, count, rounds = carry
            toks, n_emit, t_cache, d_cache, pos = spec_round(
                target_params, draft_params, last, t_cache, d_cache, pos)
            buf = jax.lax.dynamic_update_slice(buf, toks, (0, count))
            last = jnp.take_along_axis(
                toks, (n_emit - 1) * jnp.ones((b, 1), jnp.int32),
                axis=1)[:, 0]
            return (last, t_cache, d_cache, pos, buf, count + n_emit,
                    rounds + 1)

        (_, t_cache, d_cache, pos, buf, count, rounds) = jax.lax.while_loop(
            cond, body,
            (last_tok, t_cache, d_cache, pos, buf,
             jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)))
        return buf, jnp.stack([count, rounds])

    return gen


class SpeculativeDecoder:
    """Host-side generation loop around the jitted multi-round dispatch.

    One target + one draft model, greedy, batch 1. The draft cache rides
    along; the host reads one ``[R*(γ+1)]`` token buffer per dispatch —
    or, with ``fused=True``, runs the whole generation in one program
    and reads a single buffer (no mid-generation host syncs at all).
    """

    def __init__(self, target_cfg: TransformerConfig, target_params: Any,
                 draft_cfg: TransformerConfig, draft_params: Any,
                 gamma: int = 4, rounds_per_dispatch: int = 4,
                 max_seq: Optional[int] = None):
        self.tc, self.tp = target_cfg, target_params
        self.dc, self.dp = draft_cfg, draft_params
        self.gamma = int(gamma)
        self.R = int(rounds_per_dispatch)
        self.S = int(max_seq or target_cfg.max_seq)
        self._dispatch = jax.jit(
            build_speculative_dispatch(target_cfg, draft_cfg, self.gamma,
                                       self.R, self.S),
            donate_argnums=(3, 4))
        self._prefill_t = jax.jit(build_prefill(target_cfg, self.S))
        self._prefill_d = jax.jit(build_prefill(draft_cfg, self.S))
        self._fused: dict = {}  # max_new → jitted whole-generation program
        self.stats = {"rounds": 0, "tokens": 0, "dispatches": 0}

    def generate(self, prompt, max_new_tokens: int = 64,
                 fused: bool = False) -> list:
        """Greedy generation; output is token-identical to target-only
        greedy decode. ``fused=True`` runs the whole generation as one
        program (single host sync; one compile per max_new_tokens value)
        — fastest when tokens aren't consumed mid-stream."""
        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        n = prompt.shape[1]
        if not 0 < n < self.S:
            raise ValueError(f"speculative: prompt length {n} must be in "
                             f"(0, {self.S})")
        t_logits, t_cache = self._prefill_t(self.tp, jnp.asarray(prompt))
        _, d_cache = self._prefill_d(self.dp, jnp.asarray(prompt))
        first = int(jnp.argmax(t_logits[0]))
        out = [first]
        last = jnp.asarray([first], jnp.int32)
        pos = jnp.asarray(n, jnp.int32)
        if fused:
            m = max_new_tokens - 1  # minus the prefill-seeded first token
            if m > 0:
                if m not in self._fused:
                    # no donation: the fused program's outputs contain no
                    # cache-shaped array for the inputs to alias with
                    self._fused[m] = jax.jit(build_speculative_generate(
                        self.tc, self.dc, self.gamma, m, self.S))
                buf, count_rounds = self._fused[m](self.tp, self.dp, last,
                                                   t_cache, d_cache, pos)
                # both transfers in flight before either blocks (one
                # tunnel round trip instead of two)
                for arr in (buf, count_rounds):
                    getattr(arr, "copy_to_host_async", lambda: None)()
                count, rounds = (int(x) for x in np.asarray(count_rounds))
                out.extend(np.asarray(buf)[0, :count].tolist())
                self.stats["dispatches"] += 1
                self.stats["tokens"] += count
                self.stats["rounds"] += rounds
            return out[:max_new_tokens]
        while len(out) < max_new_tokens:
            buf, n_emits, last, t_cache, d_cache, pos = self._dispatch(
                self.tp, self.dp, last, t_cache, d_cache, pos)
            for arr in (buf, n_emits):
                getattr(arr, "copy_to_host_async", lambda: None)()
            n_emits = np.asarray(n_emits)
            count = int(n_emits.sum())
            if count == 0:
                break  # cache window exhausted — every round skipped
            out.extend(np.asarray(buf)[0, :count].tolist())
            self.stats["dispatches"] += 1
            self.stats["rounds"] += int((n_emits > 0).sum())
            self.stats["tokens"] += count
        return out[:max_new_tokens]

    @property
    def mean_accepted(self) -> float:
        """Average tokens emitted per executed round (1.0 = no
        speculation win; γ+1 = every draft accepted)."""
        return self.stats["tokens"] / max(1, self.stats["rounds"])


def draft_from_target(cfg: TransformerConfig, params: Any,
                      n_layers: int) -> Tuple[TransformerConfig, Any]:
    """Depth-pruned self-speculative draft: the target's FIRST
    ``n_layers`` layers (params are stacked [L, ...], so the draft is a
    zero-copy slice) sharing the embedding — no separately-trained draft
    model needed, and early layers correlate strongly with the full
    model's prediction, which is what acceptance length depends on.
    """
    if not 0 < n_layers <= cfg.n_layers:
        raise ValueError(
            f"draft_from_target: n_layers must be in (0, {cfg.n_layers}], "
            f"got {n_layers}")
    draft_cfg = dataclasses.replace(cfg, n_layers=n_layers)
    draft_params = {
        k: (v if k in ("embed", "ln_f") else v[:n_layers])
        for k, v in params.items()
    }
    return draft_cfg, draft_params
