"""SSD-MobileNet detector (benchmark config #2).

The reference decodes ``ssd_mobilenet_v2_coco.tflite`` output with its
bounding_boxes decoder (tensordec-boundingbox.c mode=mobilenet-ssd):
two tensors — box encodings [4, anchors, 1] and class scores
[classes, anchors, 1] — postprocessed against an anchor grid. This module
provides the same output contract natively: a MobileNetV2 backbone with
SSD heads over feature maps, plus the anchor grid generator the decoder
needs.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models.mobilenet_v2 import InvertedResidual, _make_divisible
from nnstreamer_tpu.tensors.types import TensorsInfo


class SSDMobileNet(nn.Module):
    num_classes: int = 91
    num_anchors_per_cell: int = 6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        # reduced MobileNetV2 backbone, keeping two feature scales
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu6(nn.BatchNorm(use_running_average=True,
                                  dtype=self.dtype)(x))
        feats = []
        for expand, out_ch, repeats, stride in [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 3, 2),
            (6, 96, 2, 1),
        ]:
            for i in range(repeats):
                x = InvertedResidual(out_ch, stride if i == 0 else 1,
                                     expand, self.dtype)(x)
            if out_ch in (96,):
                feats.append(x)  # stride-16 map
        for expand, out_ch, repeats, stride in [(6, 160, 2, 2), (6, 320, 1, 1)]:
            for i in range(repeats):
                x = InvertedResidual(out_ch, stride if i == 0 else 1,
                                     expand, self.dtype)(x)
        feats.append(x)  # stride-32 map

        boxes, scores = [], []
        k = self.num_anchors_per_cell
        for f in feats:
            b = nn.Conv(k * 4, (3, 3), padding="SAME", dtype=self.dtype)(f)
            s = nn.Conv(k * self.num_classes, (3, 3), padding="SAME",
                        dtype=self.dtype)(f)
            n = f.shape[0]
            boxes.append(b.reshape(n, -1, 4))
            scores.append(s.reshape(n, -1, self.num_classes))
        return (jnp.concatenate(boxes, axis=1).astype(jnp.float32),
                jnp.concatenate(scores, axis=1).astype(jnp.float32))


def anchor_grid(image_size: int = 300, strides=(16, 32),
                num_anchors_per_cell: int = 6) -> np.ndarray:
    """Anchor centers/sizes [anchors, 4] as (cy, cx, h, w) in [0,1] —
    consumed by the bounding_boxes decoder (the reference reads its anchor
    box-priors from a file; ours are generated to match the model)."""
    anchors = []
    scales = np.linspace(0.2, 0.9, len(strides) * num_anchors_per_cell)
    si = 0
    for stride in strides:
        # SAME-padded stride-s convs produce ceil(size/s) cells — the grid
        # must match the model's feature-map geometry exactly
        cells = -(-image_size // stride)
        for a in range(num_anchors_per_cell):
            s = scales[si]
            si += 1
            ratio = [1.0, 2.0, 0.5, 3.0, 1.0 / 3.0, 1.0][a % 6]
            h, w = s / np.sqrt(ratio), s * np.sqrt(ratio)
            ys, xs = np.meshgrid(
                (np.arange(cells) + 0.5) / cells,
                (np.arange(cells) + 0.5) / cells, indexing="ij",
            )
            grid = np.stack(
                [ys.ravel(), xs.ravel(),
                 np.full(cells * cells, h), np.full(cells * cells, w)],
                axis=1,
            )
            anchors.append(grid)
    return np.concatenate(anchors, axis=0).astype(np.float32)


def ssd_mobilenet(num_classes: int = 91, image_size: int = 300,
                  batch: int = 1, dtype=jnp.bfloat16, seed: int = 0
                  ) -> Tuple[Callable, Any, TensorsInfo, TensorsInfo]:
    model = SSDMobileNet(num_classes=num_classes, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    from nnstreamer_tpu.models._init import fast_init
    variables = fast_init(model.init, rng, dummy, seed=seed)
    b, s = jax.eval_shape(lambda p, x: model.apply(p, x), variables, dummy)
    num_anchors = b.shape[1]

    def apply_fn(params, x):
        return model.apply(params, x)

    in_info = TensorsInfo.from_str(
        f"3:{image_size}:{image_size}:{batch}", "float32")
    out_info = TensorsInfo.from_str(
        f"4:{num_anchors}:{batch},{num_classes}:{num_anchors}:{batch}",
        "float32,float32")
    return apply_fn, variables, in_info, out_info
