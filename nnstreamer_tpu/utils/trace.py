"""Tracing / profiling — per-element tracers + XLA profiler integration.

Reference: no in-tree tracer; relies on GStreamer tracer hooks consumed by
GstShark (proctime / interlatency / framerate tracers,
tools/tracing/README.md) plus per-filter latency properties. Here tracing
is in-tree (SURVEY §5 asks for exactly this):

- :class:`Tracer` attaches to a pipeline and records, per buffer:
  **proctime** (element chain duration), **interlatency** (source pts →
  element arrival), and **framerate** per element — the three GstShark
  tracers the reference's docs describe.
- Export as Chrome trace-event JSON (``chrome://tracing`` /
  Perfetto-loadable) or aggregate dicts.
- :func:`xla_profile` wraps ``jax.profiler`` so device-side traces
  (XPlane) land next to the host-side ones.

Usage::

    tracer = Tracer()
    with tracer.attach(pipe):
        pipe.run()
    tracer.summary()         # {element: {proctime_us_avg, fps, ...}}
    tracer.export_chrome("trace.json")
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, List, Optional

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.pipeline.pipeline import Pipeline


class Tracer:
    def __init__(self, max_events: int = 100_000):
        self.events: List[dict] = []
        self.max_events = max_events
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # first time each pts was seen anywhere in the pipeline — the
        # baseline for the interlatency metric (source → element delay)
        self._first_seen: Dict[int, float] = {}

    # -- hook installation ---------------------------------------------------
    @contextlib.contextmanager
    def attach(self, pipeline: Pipeline):
        """Wrap every element's chain entry with trace recording."""
        wrapped = []
        for el in pipeline.elements:
            el._chain_entry = self._wrap(el, el._chain_entry)
            wrapped.append(el)
        try:
            yield self
        finally:
            for el in wrapped:
                # drop the instance attribute so the class method resolves
                # again (no permanent shadowing)
                el.__dict__.pop("_chain_entry", None)
            # stream over (EOS or abandoned): every surviving baseline
            # belongs to a finished run — a reattach starts fresh
            with self._lock:
                self._first_seen.clear()

    def _wrap(self, el: Element, fn):
        is_sink = not el.srcpads  # terminal element: frames complete here

        def traced(pad, buf):
            t_in = time.monotonic()
            interlat_us = None
            if buf.pts is not None:
                with self._lock:
                    first = self._first_seen.setdefault(buf.pts, t_in)
                    if len(self._first_seen) > 16384:  # backstop bound
                        self._first_seen.pop(next(iter(self._first_seen)))
                interlat_us = (t_in - first) * 1e6
            ret = fn(pad, buf)
            t_out = time.monotonic()
            self._record(el.name, t_in, t_out, buf.pts, interlat_us)
            if is_sink and buf.pts is not None:
                # the frame completed — retire its baseline so the
                # backstop above only ever evicts truly-lost frames;
                # evicting oldest-INSERTED regardless of completion
                # churned live baselines on long runs and skewed
                # interlatency toward zero
                with self._lock:
                    self._first_seen.pop(buf.pts, None)
            return ret

        return traced

    def _record(self, name: str, t_in: float, t_out: float,
                pts: Optional[int], interlat_us: Optional[float] = None):
        with self._lock:
            if len(self.events) >= self.max_events:
                return
            self.events.append({
                "element": name,
                "ts_us": (t_in - self._t0) * 1e6,
                "dur_us": (t_out - t_in) * 1e6,
                "pts": pts,
                "interlatency_us": interlat_us,
            })

    # -- outputs -------------------------------------------------------------
    def summary(self) -> Dict[str, dict]:
        """Per-element proctime/framerate aggregates (GstShark metrics)."""
        agg: Dict[str, dict] = {}
        with self._lock:
            events = list(self.events)
        for ev in events:
            a = agg.setdefault(ev["element"], {
                "count": 0, "proctime_us_total": 0.0, "first_ts": ev["ts_us"],
                "last_ts": ev["ts_us"], "interlatency_us_total": 0.0,
                "interlatency_n": 0,
            })
            a["count"] += 1
            a["proctime_us_total"] += ev["dur_us"]
            a["last_ts"] = ev["ts_us"]
            if ev.get("interlatency_us") is not None:
                a["interlatency_us_total"] += ev["interlatency_us"]
                a["interlatency_n"] += 1
        for name, a in agg.items():
            a["proctime_us_avg"] = a["proctime_us_total"] / max(a["count"], 1)
            span_s = (a["last_ts"] - a["first_ts"]) / 1e6
            a["fps"] = (a["count"] - 1) / span_s if span_s > 0 else 0.0
            a["interlatency_us_avg"] = (
                a["interlatency_us_total"] / a["interlatency_n"]
                if a["interlatency_n"] else 0.0
            )
        return agg

    def export_chrome(self, path: str) -> None:
        """Chrome trace-event format (load in chrome://tracing/Perfetto).

        Each invoke is a ``ph:"X"`` slice carrying ``pts`` and
        ``interlatency_us`` as args; per-pts flow events (``s``/``t``/
        ``f``) chain a frame's slices across element tracks so Perfetto
        can follow one frame through the pipeline."""
        with self._lock:
            events = list(self.events)
        tids = {name: i for i, name in enumerate(
            sorted({ev["element"] for ev in events}))}
        trace: List[dict] = []
        flows: Dict[int, List[tuple]] = {}
        for ev in events:
            args: dict = {"pts": ev["pts"]}
            if ev.get("interlatency_us") is not None:
                args["interlatency_us"] = round(ev["interlatency_us"], 3)
            trace.append({
                "name": ev["element"],
                "cat": "element",
                "ph": "X",
                "ts": ev["ts_us"],
                "dur": ev["dur_us"],
                "pid": 1,
                "tid": tids[ev["element"]],
                "args": args,
            })
            if ev["pts"] is not None:
                flows.setdefault(ev["pts"], []).append(
                    (ev["ts_us"], tids[ev["element"]]))
        for pts, hops in flows.items():
            if len(hops) < 2:
                continue  # a frame seen on one track has nothing to link
            hops.sort()
            for i, (ts, tid) in enumerate(hops):
                ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
                flow = {"name": "frame", "cat": "frame", "ph": ph,
                        "id": pts, "ts": ts, "pid": 1, "tid": tid}
                if ph == "f":
                    flow["bp"] = "e"
                trace.append(flow)
        with open(path, "w") as f:
            json.dump({"traceEvents": trace}, f)


@contextlib.contextmanager
def xla_profile(logdir: str):
    """Capture an XLA device trace around a pipeline run (jax profiler
    XPlane; view with TensorBoard or xprof). The TPU-side counterpart of
    :class:`Tracer`'s host-side events."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
