"""Shared utilities: runtime statistics, tracing hooks."""

from nnstreamer_tpu.utils.stats import InvokeStats  # noqa: F401
