"""Runtime latency/throughput instrumentation.

The reference measures itself around every filter invoke
(``prepare_statistics``/``record_statistics``, tensor_filter.c:325-423):
a window of recent invoke latencies (avg over the last ~10 exposed as the
``latency`` property, µs) and a throughput estimate (outputs/sec ×1000,
``throughput`` property), plus cumulative per-framework counters
(``GstTensorFilterFrameworkStatistics``, nnstreamer_plugin_api_filter.h:
162-174). This module is the same capability for every element: call
:meth:`InvokeStats.record` around the hot call and read ``latency_us`` /
``throughput_milli`` at any time.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Optional, Tuple


class InvokeStats:
    """Windowed latency + throughput tracker (thread-safe).

    ``window`` mirrors the reference's recent-sample window; samples older
    than ``max_age_s`` are dropped from the throughput estimate the way the
    reference prunes stale entries (tensor_filter.c:407-417).
    """

    def __init__(self, window: int = 10, max_age_s: float = 10.0):
        self.window = window
        self.max_age_s = max_age_s
        self._lat: Deque[float] = collections.deque(maxlen=window)
        self._stamps: Deque[float] = collections.deque()
        self._lock = threading.Lock()
        self.total_invokes = 0
        self.total_latency_s = 0.0

    def measure(self):
        """Context manager measuring one invoke."""
        return _Measure(self)

    def record(self, latency_s: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._lat.append(latency_s)
            self._stamps.append(now)
            cutoff = now - self.max_age_s
            while self._stamps and self._stamps[0] < cutoff:
                self._stamps.popleft()
            self.total_invokes += 1
            self.total_latency_s += latency_s

    # -- reference-named read-outs ------------------------------------------
    @property
    def latency_us(self) -> int:
        """Average invoke latency in µs over the recent window (reference
        ``latency`` property)."""
        with self._lock:
            if not self._lat:
                return 0
            return int(sum(self._lat) / len(self._lat) * 1e6)

    @property
    def throughput_milli(self) -> int:
        """Outputs/sec ×1000 over the recent window (reference ``throughput``
        property)."""
        with self._lock:
            n = len(self._stamps)
            if n < 2:
                return 0
            span = self._stamps[-1] - self._stamps[0]
            if span <= 0:
                return 0
            return int((n - 1) / span * 1000)

    def snapshot(self) -> dict:
        # read the properties outside the lock — they acquire it themselves
        # (the lock is non-reentrant)
        return {
            "latency_us": self.latency_us,
            "throughput_milli": self.throughput_milli,
            "total_invokes": self.total_invokes,
            "total_latency_s": self.total_latency_s,
        }


class _Measure:
    def __init__(self, stats: InvokeStats):
        self.stats = stats
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        now = time.monotonic()
        self.stats.record(now - self.t0, now)
        return False
