"""Checkpoint / resume for stateful streams and models.

The reference has no training checkpoints (SURVEY §5: "none"); its
stateful-stream state lives in tensor_repo slots and aggregator adapters.
The TPU build makes that durable:

- :func:`save_params` / :func:`load_params` — model params as flax
  msgpack (what ``tensor_filter framework=jax model=x.msgpack
  custom=module:<factory>`` loads);
- :func:`save_stream_state` / :func:`restore_stream_state` — snapshot of
  the global tensor_repo (recurrent hidden state), so an RNN/LSTM
  pipeline can resume exactly where it stopped;
- :class:`OrbaxCheckpointer` — optional orbax-backed versioned
  checkpoints for training loops (transformer train step), gated on
  orbax availability.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np


def save_params(params: Any, path: str) -> None:
    """Serialize a params pytree to flax msgpack."""
    from flax import serialization

    with open(path, "wb") as f:
        f.write(serialization.to_bytes(params))


def load_params(params_template: Any, path: str) -> Any:
    from flax import serialization

    with open(path, "rb") as f:
        return serialization.from_bytes(params_template, f.read())


def save_stream_state(path: str, repo=None, extra: Optional[Dict] = None
                      ) -> None:
    """Snapshot repo slots (+ anything in ``extra``) to disk. Device
    arrays are pulled to host; restore re-uploads lazily on first use."""
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO

    repo = repo if repo is not None else GLOBAL_REPO
    state = {"repo": repo.snapshot(), "extra": extra or {}}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)  # atomic publish


def restore_stream_state(path: str, repo=None) -> Dict:
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO

    repo = repo if repo is not None else GLOBAL_REPO
    with open(path, "rb") as f:
        state = pickle.load(f)
    repo.restore(state["repo"])
    return state.get("extra", {})


class OrbaxCheckpointer:
    """Versioned train-state checkpoints via orbax (optional dep)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any) -> None:
        self.manager.save(step, args=self._ocp.args.StandardSave(state))
        self.manager.wait_until_finished()

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None) -> Any:
        step = self.latest_step() if step is None else step
        if template is not None:
            return self.manager.restore(
                step, args=self._ocp.args.StandardRestore(template))
        return self.manager.restore(step)

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()
