"""Platform selection helper for scripts and examples.

Hosts may preset ``JAX_PLATFORMS`` to a plugin this process cannot
initialize (e.g. a TPU tunnel registered only for some interpreters).
:func:`ensure_jax_platform` commits the preset backend if it works and
falls back to CPU XLA otherwise — call it before any other jax work.
"""

from __future__ import annotations


def ensure_jax_platform() -> str:
    """Initialize the jax backend, falling back to CPU if the preset
    platform is unusable. Returns the platform name in use."""
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
