"""Platform selection helpers for scripts, examples, and bench.

Hosts may preset ``JAX_PLATFORMS`` to a plugin this process cannot use —
either one that raises at init, or a remote-tunnel backend that WEDGES
during PJRT client creation (blocks forever instead of raising). So a
non-CPU preset is probed in a SUBPROCESS with a timeout before this
process commits to it. The probe child runs in its own session and the
whole process group is killed on timeout, so a wedged plugin (or a
helper process it forked holding our pipe) cannot hang the probe itself.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Optional

#: default probe budget — tunneled TPU backends can legitimately take
#: minutes to create their PJRT client (same default as bench)
DEFAULT_PROBE_TIMEOUT = 300.0


def probe_jax_platform(timeout_s: Optional[float] = None) -> Optional[str]:
    """Initialize jax in a subprocess; return its platform name, or None
    if initialization failed or wedged past the timeout."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("NNSTPU_PROBE_TIMEOUT",
                                         str(DEFAULT_PROBE_TIMEOUT)))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        return None
    if proc.returncode != 0:
        return None
    return out.strip().splitlines()[-1] if out.strip() else None


def ensure_jax_platform(probe_timeout: Optional[float] = None) -> str:
    """Commit a working jax backend (preset platform if healthy, else CPU)
    and return the platform name in use. Call before any other jax work."""
    preset = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if preset == "cpu":
        # nothing exotic to probe; in-process init cannot wedge on CPU
        import jax

        return jax.devices()[0].platform

    healthy = probe_jax_platform(probe_timeout)

    import jax

    if healthy is None:
        jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform
