"""Platform selection helper for scripts and examples.

Hosts may preset ``JAX_PLATFORMS`` to a plugin this process cannot use —
either one that raises at init, or a remote-tunnel backend that WEDGES
during PJRT client creation (blocks forever instead of raising). So the
preset platform is probed in a SUBPROCESS with a timeout, and only a
healthy probe lets this process initialize it; anything else falls back
to CPU XLA before the in-process backend is committed.
"""

from __future__ import annotations

import os
import subprocess
import sys


def ensure_jax_platform(probe_timeout: float | None = None) -> str:
    """Commit a working jax backend (preset platform if healthy, else CPU)
    and return the platform name in use. Call before any other jax work."""
    if probe_timeout is None:
        probe_timeout = float(os.environ.get("NNSTPU_PROBE_TIMEOUT", "120"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=probe_timeout, text=True,
        )
        healthy = proc.returncode == 0
    except subprocess.TimeoutExpired:
        healthy = False

    import jax

    if not healthy:
        jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform
