"""Platform selection helpers for scripts, examples, and bench.

Hosts may preset ``JAX_PLATFORMS`` to a plugin this process cannot use —
either one that raises at init, or a remote-tunnel backend that WEDGES
during PJRT client creation (blocks forever instead of raising). So a
non-CPU preset is probed in a SUBPROCESS with a timeout before this
process commits to it. The probe child runs in its own session and the
whole process group is killed on timeout, so a wedged plugin (or a
helper process it forked holding our pipe) cannot hang the probe itself.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional

#: default probe budget — tunneled TPU backends can legitimately take
#: minutes to create their PJRT client (same default as bench)
DEFAULT_PROBE_TIMEOUT = 300.0

#: how long a cached probe verdict stays valid (seconds); override with
#: NNSTPU_PROBE_CACHE_TTL, disable caching with NNSTPU_PROBE_NOCACHE=1
DEFAULT_PROBE_CACHE_TTL = 600.0


def _probe_cache_path(preset: str) -> str:
    tag = "".join(c if c.isalnum() else "_" for c in preset) or "default"
    return os.path.join(tempfile.gettempdir(),
                        f"nnstpu_probe_{os.getuid()}_{tag}.json")


def _probe_cache_get(preset: str) -> Optional[dict]:
    if os.environ.get("NNSTPU_PROBE_NOCACHE"):
        return None
    try:
        ttl = float(os.environ.get("NNSTPU_PROBE_CACHE_TTL",
                                   str(DEFAULT_PROBE_CACHE_TTL)))
    except ValueError:
        ttl = DEFAULT_PROBE_CACHE_TTL
    path = _probe_cache_path(preset)
    try:
        # st_mtime is wall-clock, so the freshness check must be too —
        # the cache file outlives the process, and no monotonic epoch
        # spans process restarts. This is the documented exception to
        # the monotonic-clock rule (NNS101). A negative age means the
        # clock was stepped backwards since the file was written; the
        # file is then arbitrarily old in real time, so treat it as
        # stale instead of trusting it for another full TTL.
        wall_age = time.time() - os.stat(path).st_mtime
        if not 0 <= wall_age <= ttl:
            return None
        with open(path) as f:
            entry = json.load(f)
        return entry if isinstance(entry, dict) else None
    except (OSError, ValueError):
        return None


def _probe_cache_put(preset: str, platform: Optional[str]) -> None:
    if os.environ.get("NNSTPU_PROBE_NOCACHE"):
        return
    path = _probe_cache_path(preset)
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"platform": platform}, f)
        os.replace(tmp, path)
    except OSError:
        pass


def probe_jax_platform(timeout_s: Optional[float] = None) -> Optional[str]:
    """Initialize jax in a subprocess; return its platform name, or None
    if initialization failed or wedged past the timeout."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("NNSTPU_PROBE_TIMEOUT",
                                         str(DEFAULT_PROBE_TIMEOUT)))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        return None
    if proc.returncode != 0:
        return None
    return out.strip().splitlines()[-1] if out.strip() else None


def ensure_jax_platform(probe_timeout: Optional[float] = None) -> str:
    """Commit a working jax backend (preset platform if healthy, else CPU)
    and return the platform name in use. Call before any other jax work.

    An explicit ``cpu`` preset initializes in-process directly (CPU init
    cannot wedge). Everything else is probed — including an UNSET preset,
    because jax's no-preset plugin auto-discovery initializes any installed
    accelerator plugin first and can wedge exactly like an explicit one
    (a sitecustomize may even force the platform at interpreter boot).
    Probe verdicts are cached in a temp file keyed by the preset (TTL
    ``NNSTPU_PROBE_CACHE_TTL``, default 600 s) so repeated example/bench
    invocations don't re-pay the subprocess jax import or a tunneled
    backend's PJRT init.
    """
    preset = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if preset == "cpu":
        import jax

        return jax.devices()[0].platform

    cached = _probe_cache_get(preset)
    if cached is not None:
        healthy = cached.get("platform")
    else:
        healthy = probe_jax_platform(probe_timeout)
        _probe_cache_put(preset, healthy)

    import jax

    if healthy is None:
        jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform
