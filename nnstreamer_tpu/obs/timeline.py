"""Frame-ledger timeline: per-frame lifecycle spans across the async
substrate (lanes, queues, scheduler, dispatch window, transfers).

The PR-1 ``utils/trace.py`` Tracer wraps synchronous ``_chain_entry``
calls — one COMPLETE slice per element invoke — which was the whole
story when the pipeline WAS its chain calls. Everything built since is
asynchronous: DispatchWindow keeps K device batches in flight, lane
workers process frames out of order behind a reorder buffer, the SLO
scheduler holds frames in an EDF heap and sheds them, DeviceBuffers
defer their D2H to the sink. None of that shows up in a chain-wrapped
trace. This module records where a FRAME's time actually goes.

Recording model
---------------
A :class:`Timeline` is installed process-wide (``ACTIVE``). The source
thread stamps a monotone sequence id (``meta["trace_seq"]``) on every
frame — the same single-writer monotone-id discipline the lane executor
already uses for reorder reassembly — and instrumentation points across
the stack append typed spans keyed by that id. Each recording thread
appends into its own bounded ring (``deque(maxlen=capacity)``): no
lock, no allocation beyond the tuple, GIL-atomic append. Export drains
every ring, so a span is attributed to the thread that recorded it
(lane workers, queue drains, the source loop each get their own track).

With no timeline installed (``ACTIVE is None`` — the default) every
instrumentation site is a single module-attribute read and an ``is
None`` test: the off path stays byte-identical and effectively free,
matching the ``NNSTPU_RESIDENT`` / ``NNSTPU_LANES`` kill-switch
discipline.

Stage semantics (the frame ledger)
----------------------------------
The canonical span kinds in :data:`STAGES` tile a frame's critical
path, so their per-frame sums reconcile with the sink's end-to-end
latency:

- ``ingest``      source ``create()`` → first queue entry (host
                  preprocessing, minus any reorder-buffer wait)
- ``lane_reorder``time parked in the lane reorder buffer
- ``queue_wait``  FIFO queue residency (entry → drain pop)
- ``sched_hold``  EDF-heap residency in a scheduler-mode queue
- ``fence_wait``  dispatch-window fence block for the frame's own entry
- ``shard``       mesh placement of the frame's tensors onto the serving
                  mesh (sharded fused regions only; zero/absent on
                  single-device pipelines and matched hand-offs)
- ``device``      filter/fused-region invoke dispatch
- ``d2h``         the sanctioned ``to_host()`` materialization block
- ``decode``      tensor→media decode (host part)
- ``sink``        sink-side completion work after materialization

Non-tiling kinds (``h2d``, ``lane_exec``, ``lane_stall``) and instant
events (``sched_reject``, ``sched_shed``, ``sched_revoked``,
``submit``) appear in the exported trace but are excluded from the
reconciliation sum — they overlap the stages above in wall time.

Export
------
:meth:`Timeline.to_chrome` emits Chrome trace-event JSON that Perfetto
loads directly: one process, one named thread track per recording
thread / lane / queue, ``ph:"X"`` slices with ``args`` carrying the
frame seq, ``s``/``t``/``f`` flow events linking one frame across
tracks, and ``b``/``e`` async spans for dispatch-window inflight slots.
:meth:`stage_breakdown` aggregates the same records into per-stage
means that must sum to ~e2e; :meth:`variance_report` attributes
warm-run spread to its dominant stage. :func:`jax_correlation` runs
``jax.profiler`` over the same window so the XLA device trace can be
lined up with the frame ledger in one Perfetto session.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: meta key carrying the frame's trace context: a monotone sequence id
#: stamped once by the source thread (single writer, like the lane
#: executor's ``lane_seq``)
TRACE_SEQ_META = "trace_seq"

#: span kinds that tile a frame's critical path — the stage_breakdown /
#: reconciliation set, in pipeline order
LOCAL_STAGES: Tuple[str, ...] = ("ingest", "lane_reorder", "queue_wait",
                                 "sched_hold", "fence_wait", "shard",
                                 "device", "d2h", "decode", "sink")

#: distributed-hop stages spliced into the CLIENT ledger by
#: elements/query.py when cross-hop tracing is armed (obs/distributed):
#: outbound wire time, the remote pipeline's queue/device residency, the
#: remote remainder (decode/sink/unattributed), and inbound wire time.
#: All five are anchored inside the client's observed RTT window — raw
#: remote clocks are never compared against local ones — and stay
#: zero-valued (absent) on single-process pipelines, so every consumer
#: keyed off STAGES (flight quantiles, gauges, MAD attribution,
#: breakdowns) names remote stages without further wiring.
DIST_STAGES: Tuple[str, ...] = ("hop_send", "remote_queue",
                                "remote_device", "remote_other",
                                "hop_recv")

STAGES: Tuple[str, ...] = LOCAL_STAGES + DIST_STAGES

_ENV = "NNSTPU_TRACE"

#: the process-wide active timeline; ``None`` means tracing is OFF and
#: every instrumentation site reduces to one attribute read + is-None
#: test. Hot paths read this directly (``_timeline.ACTIVE``).
ACTIVE: Optional["Timeline"] = None


def trace_enabled() -> bool:
    """True when ``NNSTPU_TRACE`` asks for tracing (any non-empty value
    except the usual falsy spellings; a value that is not a boolean
    spelling is taken as the export path)."""
    v = os.environ.get(_ENV, "").strip()
    return bool(v) and v.lower() not in ("0", "false", "no", "off")


def env_export_path() -> Optional[str]:
    """The export path carried in ``NNSTPU_TRACE``, if it names one."""
    v = os.environ.get(_ENV, "").strip()
    if not v or v.lower() in ("0", "false", "no", "off", "1", "true",
                              "yes", "on"):
        return None
    return v


def active() -> Optional["Timeline"]:
    return ACTIVE


def activate(capacity: int = 1 << 16) -> "Timeline":
    """Install a fresh process-wide timeline and return it."""
    global ACTIVE
    tl = Timeline(capacity)
    ACTIVE = tl
    return tl


def deactivate() -> None:
    global ACTIVE
    ACTIVE = None


@contextmanager
def tracing(capacity: int = 1 << 16):
    """Scoped activation: ``with tracing() as tl: pipe.run(...)``."""
    tl = activate(capacity)
    try:
        yield tl
    finally:
        if ACTIVE is tl:
            deactivate()


def maybe_activate_env() -> Optional["Timeline"]:
    """``Pipeline.start()`` hook: honor ``NNSTPU_TRACE`` without code
    changes. Idempotent; an explicitly installed timeline wins."""
    if ACTIVE is not None:
        return ACTIVE
    if not trace_enabled():
        return None
    tl = activate()
    tl.export_path = env_export_path()
    tl._env_owned = True
    return tl


def maybe_export_env() -> None:
    """``Pipeline.stop()`` hook: export + retire an env-owned timeline
    (``NNSTPU_TRACE=/path/to/trace.json``)."""
    tl = ACTIVE
    if tl is None or not tl._env_owned:
        return
    if tl.export_path:
        try:
            tl.export_chrome(tl.export_path)
        except OSError:
            pass  # an unwritable path must not take down pipeline stop
    deactivate()


@contextmanager
def jax_correlation(logdir: str):
    """Run ``jax.profiler`` over the same window as the active timeline
    so the XLA device trace and the frame ledger share a wall-clock
    span and can be loaded side by side in Perfetto. Degrades to a
    no-op when the profiler is unavailable."""
    started = False
    try:
        import jax

        jax.profiler.start_trace(logdir)
        started = True
    except Exception:  # noqa: BLE001 — profiling is best-effort
        started = False
    try:
        yield
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # nns-lint: disable=NNS104 -- stop_trace after a successful start can only fail at teardown; the ledger export must still proceed
                pass


class _RingAnchor:
    """Weakref-able token parked in a recording thread's thread-local
    dict; its finalizer retires the thread's ring (see ``_ring``)."""

    __slots__ = ("__weakref__",)


def _retire_ring(tl_ref: "weakref.ref", entry: Tuple[str, deque]) -> None:
    tl = tl_ref()
    if tl is not None:
        tl._retire(entry)


class Timeline:
    """Low-overhead frame-ledger recorder (see module docstring)."""

    def __init__(self, capacity: int = 1 << 16):
        self.capacity = int(capacity)
        self.epoch = time.monotonic()
        self.export_path: Optional[str] = None
        self._env_owned = False
        self._seq = itertools.count()  # next() is GIL-atomic
        self._local = threading.local()
        #: [(thread_name, ring)] — registry of every LIVE thread's ring;
        #: appended once per recording thread under the lock, drained
        #: at export, removed when the thread dies (see ``_ring``)
        self._rings: List[Tuple[str, deque]] = []
        self._rings_lock = threading.Lock()
        #: records salvaged from dead threads' rings — supervised lane
        #: restarts spin up fresh worker threads per crash cycle, so
        #: without retirement ``_rings`` grows one entry per restart
        #: forever; bounded like any single ring
        self._retired: deque = deque(maxlen=self.capacity)
        #: dispatch-window inflight slots: ("b"/"e", name, id, t, track)
        self._async: deque = deque(maxlen=4 * self.capacity)

    # -- recording (hot path) ------------------------------------------------
    def next_seq(self) -> int:
        return next(self._seq)

    def _ring(self) -> deque:
        r = getattr(self._local, "ring", None)
        if r is None:
            r = deque(maxlen=self.capacity)
            entry = (threading.current_thread().name, r)
            with self._rings_lock:
                self._rings.append(entry)
            # Unregister at thread death: the anchor lives only in this
            # thread's thread-local dict, so CPython drops it when the
            # thread exits and the finalizer moves the ring's records
            # into the bounded ``_retired`` store. Pipeline.stop() joins
            # workers before export, so post-join exports still see
            # every span; what this prevents is ``_rings`` growing one
            # dead entry per supervised lane restart.
            anchor = _RingAnchor()
            weakref.finalize(anchor, _retire_ring, weakref.ref(self),
                             entry)
            self._local.ring = r
            self._local.anchor = anchor
        return r

    def _retire(self, entry: Tuple[str, deque]) -> None:
        name, ring = entry
        with self._rings_lock:
            try:
                self._rings.remove(entry)
            except ValueError:
                return  # clear()/re-entry already handled it
            for rec in ring:
                self._retired.append((name,) + rec)

    def span(self, kind: str, seq: Optional[int], t0: float, t1: float,
             track: Optional[str] = None, **args) -> None:
        """Record a duration span [t0, t1) attributed to frame ``seq``."""
        self._ring().append((kind, seq, t0, t1, track, args or None))

    def mark(self, kind: str, seq: Optional[int],
             t: Optional[float] = None, track: Optional[str] = None,
             **args) -> None:
        """Record an instant event (shed/reject decisions, submits)."""
        if t is None:
            t = time.monotonic()
        self._ring().append((kind, seq, t, None, track, args or None))

    def async_begin(self, name: str, aid: int,
                    t: Optional[float] = None,
                    track: str = "dispatch") -> None:
        self._async.append(
            ("b", name, aid, time.monotonic() if t is None else t, track))

    def async_end(self, name: str, aid: int,
                  t: Optional[float] = None,
                  track: str = "dispatch") -> None:
        self._async.append(
            ("e", name, aid, time.monotonic() if t is None else t, track))

    def clear(self) -> None:
        """Drop recorded events (rings stay registered; epoch advances
        so a re-used timeline exports a fresh window)."""
        with self._rings_lock:
            rings = list(self._rings)
            self._retired.clear()
        for _, r in rings:
            r.clear()
        self._async.clear()
        self.epoch = time.monotonic()

    # -- aggregation ---------------------------------------------------------
    def _snapshot(self) -> List[tuple]:
        """All records as (thread, kind, seq, t0, t1, track, args),
        time-ordered."""
        with self._rings_lock:
            rings = list(self._rings)
            retired = list(self._retired)
        out: List[tuple] = list(retired)
        for tname, ring in rings:
            for rec in list(ring):
                out.append((tname,) + rec)
        out.sort(key=lambda r: r[3])
        return out

    def frame_ledger(self, skip_frames: int = 0
                     ) -> Dict[int, Dict[str, float]]:
        """Per-frame stage durations (seconds) keyed by trace seq; a
        frame that reached the sink also carries its measured ``e2e``.
        ``skip_frames`` drops the first N frames (warm-up exclusion)."""
        frames: Dict[int, Dict[str, float]] = {}
        for _, kind, seq, t0, t1, _, args in self._snapshot():
            if seq is None or t1 is None:
                continue
            d = frames.setdefault(seq, {})
            d[kind] = d.get(kind, 0.0) + (t1 - t0)
            if args and "e2e_s" in args:
                d["e2e"] = float(args["e2e_s"])
        for s in sorted(frames)[:skip_frames]:
            del frames[s]
        return frames

    def frame_stages(self, seq: int) -> Dict[str, float]:
        """Stage durations (seconds) for ONE frame — the scan-based
        span-vector source a query server uses for remote egress when
        no flight recorder (with its O(1) per-frame accumulator) is
        installed."""
        out: Dict[str, float] = {}
        for _, kind, s, t0, t1, _, _ in self._snapshot():
            if s == seq and t1 is not None:
                out[kind] = out.get(kind, 0.0) + (t1 - t0)
        return out

    def stage_breakdown(self, skip_frames: int = 0) -> Dict[str, Any]:
        """Mean per-frame seconds spent in each canonical stage, over
        frames that completed (have a sink e2e record). ``covered_ms``
        is the sum of the stage means; ``reconciliation`` is
        covered/e2e — ~1.0 means the ledger accounts for the frame's
        whole life, a gap shows as ``unattributed_ms``."""
        frames = self.frame_ledger(skip_frames)
        done = [d for d in frames.values() if "e2e" in d]
        n = len(done)
        if n == 0:
            return {"frames": 0, "stages_ms": {}, "e2e_mean_ms": 0.0,
                    "covered_ms": 0.0, "unattributed_ms": 0.0,
                    "reconciliation": 0.0}
        stages = {k: sum(d.get(k, 0.0) for d in done) / n * 1e3
                  for k in STAGES}
        e2e = sum(d["e2e"] for d in done) / n * 1e3
        covered = sum(stages.values())
        return {
            "frames": n,
            "stages_ms": {k: round(v, 4) for k, v in stages.items()},
            "e2e_mean_ms": round(e2e, 4),
            "covered_ms": round(covered, 4),
            "unattributed_ms": round(max(e2e - covered, 0.0), 4),
            "reconciliation": round(covered / e2e, 4) if e2e > 0 else 0.0,
        }

    def variance_report(self, skip_frames: int = 0) -> Dict[str, Any]:
        """Attribute e2e spread to its dominant stage: per-stage MAD of
        the per-frame durations (robust — one cold outlier cannot own
        the report), ranked; ``dominant_share`` is the winner's MAD as
        a fraction of the e2e MAD."""
        frames = self.frame_ledger(skip_frames)
        done = [d for d in frames.values() if "e2e" in d]
        if len(done) < 2:
            return {"frames": len(done), "e2e_mad_ms": 0.0,
                    "stage_mad_ms": {}, "dominant_stage": None,
                    "dominant_share": 0.0}

        def _mad(vals: List[float]) -> float:
            vals = sorted(vals)
            med = vals[len(vals) // 2]
            dev = sorted(abs(v - med) for v in vals)
            return dev[len(dev) // 2]

        stage_mad = {k: _mad([d.get(k, 0.0) for d in done]) * 1e3
                     for k in STAGES}
        e2e_mad = _mad([d["e2e"] for d in done]) * 1e3
        dominant = max(stage_mad, key=lambda k: stage_mad[k])
        if stage_mad[dominant] <= 0.0:
            dominant = None
        return {
            "frames": len(done),
            "e2e_mad_ms": round(e2e_mad, 4),
            "stage_mad_ms": {k: round(v, 4)
                             for k, v in stage_mad.items()},
            "dominant_stage": dominant,
            "dominant_share": round(stage_mad[dominant] / e2e_mad, 4)
            if dominant and e2e_mad > 0 else 0.0,
        }

    # -- export --------------------------------------------------------------
    def _us(self, t: float) -> float:
        return round((t - self.epoch) * 1e6, 3)

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable): named thread
        tracks, ``X`` slices with frame-seq args, flow events following
        each frame across tracks, async inflight-slot spans.

        Spans carrying an ``endpoint`` arg (the spliced remote-hop
        stages from obs/distributed) render under their own *process*
        track — pid 1 stays the local process, each distinct endpoint
        gets the next pid — and the per-frame flow chain crosses those
        process boundaries, so a distributed timeline loads as one
        flame graph instead of colliding tids."""
        recs = self._snapshot()
        pids: Dict[str, int] = {"": 1}
        tids: Dict[Tuple[int, str], int] = {}
        tid_next: Dict[int, int] = {}

        def _pid(endpoint: Optional[str]) -> int:
            key = str(endpoint) if endpoint else ""
            p = pids.get(key)
            if p is None:
                p = pids[key] = len(pids) + 1
            return p

        def _tid(pid: int, track: str) -> int:
            t = tids.get((pid, track))
            if t is None:
                t = tid_next.get(pid, 0) + 1
                tid_next[pid] = t
                tids[(pid, track)] = t
            return t

        events: List[dict] = []
        flows: Dict[int, List[Tuple[float, int, int]]] = {}
        for thread, kind, seq, t0, t1, track, args in recs:
            track = track or thread
            a: Dict[str, Any] = {"seq": seq}
            if args:
                a.update(args)
            pid = _pid(a.get("endpoint"))
            tid = _tid(pid, track)
            if t1 is None:
                events.append({"name": kind, "cat": "timeline",
                               "ph": "i", "s": "t", "ts": self._us(t0),
                               "pid": pid, "tid": tid, "args": a})
            else:
                events.append({"name": kind, "cat": "timeline",
                               "ph": "X", "ts": self._us(t0),
                               "dur": max(round((t1 - t0) * 1e6, 3), 0.0),
                               "pid": pid, "tid": tid, "args": a})
                if seq is not None:
                    flows.setdefault(seq, []).append((t0, pid, tid))
        # flow events: one arrow chain per frame across its tracks (and,
        # for hop spans, across endpoint processes) — the "follow this
        # frame" affordance in Perfetto
        for seq, hops in flows.items():
            if len(hops) < 2:
                continue
            hops.sort()
            for i, (t0, pid, tid) in enumerate(hops):
                ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
                ev = {"name": "frame", "cat": "frame", "ph": ph,
                      "id": seq, "ts": self._us(t0), "pid": pid,
                      "tid": tid}
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
        for ph, name, aid, t, track in list(self._async):
            events.append({"name": name, "cat": "inflight", "ph": ph,
                           "id": aid, "ts": self._us(t), "pid": 1,
                           "tid": _tid(1, track)})
        meta: List[dict] = []
        for endpoint, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": "nnstreamer_tpu" if pid == 1
                                  else f"endpoint {endpoint}"}})
        for (pid, track), tid in sorted(tids.items(),
                                        key=lambda kv: (kv[0][0], kv[1])):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": track}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
