"""Always-on tail-latency flight recorder.

The Timeline (``obs/timeline.py``) answers "where did this frame's time
go?" — but only when someone turned tracing on BEFORE the outlier
happened. BENCH_r05's warm runs swing 141–479 fps and the saturation
p99 sits near 5 s; by the time anyone re-runs with ``NNSTPU_TRACE`` the
offending frame is gone. This module keeps a black-box recorder running
on every pipeline, always:

- :class:`FlightRecorder` is a :class:`~.timeline.Timeline` subclass
  that ``Pipeline.start()`` installs as the process-wide ``ACTIVE``
  ledger whenever no explicit/env timeline claimed the slot. Every
  existing span site feeds it unchanged — there are no new hot-path
  hooks — and it folds each frame's stage spans into a compact bounded
  stage-vector ring as the sink completes them.
- Per-stage and end-to-end latency distributions are tracked with P²
  streaming quantiles (``obs/quantiles.py`` — five markers per
  quantile, no sample storage) and exported as ``nns_stage_p50_ms`` /
  ``nns_stage_p99_ms`` gauges; with an SLO budget present, fast/slow
  burn-rate windows drive ``nns_slo_burn_rate`` and rate-limited bus
  warnings.
- Tail events — frame e2e above k× the rolling median, an SLO deadline
  breach, any fault mark, a watchdog trip — arm a *pending dump*; once
  the post-window frames have completed (so the dump shows what
  happened AFTER the offender too), the surrounding window of full span
  detail is written to a timestamped JSON file under
  ``--flight-dir`` / ``NNSTPU_FLIGHT``, rate-limited so a saturated
  pipeline produces one dump per interval, not one per frame.
- The attribution engine is the continuous version of the Timeline's
  ``variance_report``: per-stage MAD over the completed-frame ring
  names the dominant-spread stage in ``metrics_snapshot()`` and the
  post-EOS footer, and turns it into advisory scheduler hints
  (``lanes_hint`` for ingest-dominated spread, inflight / batch_cap
  pressure for fence- and hold-dominated spread).

Kill switch: ``NNSTPU_FLIGHT=0`` (or false/no/off) disables the
recorder entirely — ``ACTIVE`` stays ``None`` and the byte-identical
off path is exactly what it was before this module existed. Unset means
recorder ON, dumps OFF; a path value enables dumps into that directory.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import timeline as _timeline
from .quantiles import BurnRateWindow, P2Quantile
from .registry import get_registry

_ENV = "NNSTPU_FLIGHT"
_FALSY = ("0", "false", "no", "off")
_TRUTHY = ("1", "true", "yes", "on")

#: ring capacity for completed-frame stage vectors (attribution window)
_VECTOR_CAP = 512
#: cap on in-flight (not yet sink-completed) frame accumulators
_FRAMES_CAP = 2048
#: remembered dump paths (for snapshots/tests; files persist on disk)
_DUMPS_CAP = 16


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def flight_enabled() -> bool:
    """False only when ``NNSTPU_FLIGHT`` is an explicit falsy spelling —
    the recorder is on by default (that is the point of a black box)."""
    v = os.environ.get(_ENV, "").strip()
    return not (v and v.lower() in _FALSY)


def env_dump_dir() -> Optional[str]:
    """The dump directory carried in ``NNSTPU_FLIGHT``, if it names one
    (boolean spellings keep the recorder on with dumps off)."""
    v = os.environ.get(_ENV, "").strip()
    if not v or v.lower() in _FALSY + _TRUTHY:
        return None
    return v


class FlightRecorder(_timeline.Timeline):
    """Bounded always-on frame ledger with tail-event dump, streaming
    SLO quantiles, and continuous variance attribution."""

    def __init__(self, capacity: int = 4096, *,
                 dump_dir: Optional[str] = None,
                 slo_budget_s: Optional[float] = None,
                 tail_k: Optional[float] = None,
                 window_frames: Optional[int] = None,
                 min_interval_s: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 pipeline=None):
        super().__init__(capacity)
        self.dump_dir = dump_dir
        self.slo_budget_s = slo_budget_s
        #: a frame is a tail event when its e2e exceeds k× rolling median
        self.tail_k = tail_k if tail_k is not None else \
            _env_float("NNSTPU_FLIGHT_K", 4.0)
        #: frames of full span detail kept around the offender in a dump
        self.window_frames = int(window_frames) if window_frames else \
            int(_env_float("NNSTPU_FLIGHT_WINDOW", 8))
        #: minimum seconds between dump files (rate limiter)
        self.min_interval_s = min_interval_s if min_interval_s is not None \
            else _env_float("NNSTPU_FLIGHT_INTERVAL_S", 30.0)
        #: completions before the rolling-median trigger arms (a cold
        #: first frame must not dump)
        self.min_samples = int(min_samples) if min_samples else \
            int(_env_float("NNSTPU_FLIGHT_MIN_SAMPLES", 16))
        self.pipeline_name = getattr(pipeline, "name", None) or "pipeline"
        self._pipe_ref = weakref.ref(pipeline) if pipeline is not None \
            else None

        self._fl_lock = threading.Lock()
        # per-stage + end-to-end streaming quantiles, pre-created so
        # gauge callbacks read them without taking the lock
        self._q: Dict[str, Dict[str, P2Quantile]] = {
            name: {"p50": P2Quantile(0.5), "p99": P2Quantile(0.99)}
            for name in _timeline.STAGES + ("e2e", "e2e_admitted")
        }
        #: completed per-frame stage vectors — the attribution window
        self._vectors: deque = deque(maxlen=_VECTOR_CAP)
        #: seq -> accumulating stage durations for in-flight frames
        self._frames: Dict[int, Dict[str, float]] = {}
        self._completed = 0
        self._rolling_med: Optional[float] = None

        # SLO burn: fast window catches an active incident, slow window
        # confirms it is material; warn only when both burn hot
        self.burn_fast = BurnRateWindow(_env_float(
            "NNSTPU_FLIGHT_BURN_FAST_S", 5.0))
        self.burn_slow = BurnRateWindow(_env_float(
            "NNSTPU_FLIGHT_BURN_SLOW_S", 60.0))
        self.burn_warn_threshold = _env_float(
            "NNSTPU_FLIGHT_BURN_WARN", 2.0)
        self._last_warn_mono: Optional[float] = None

        # tail-event dump machinery
        self._pending: Optional[Dict[str, Any]] = None
        self._last_dump_mono: Optional[float] = None
        self.dump_paths: deque = deque(maxlen=_DUMPS_CAP)
        self.dump_count = 0
        self.suppressed_dumps = 0
        self.trigger_counts: Dict[str, int] = {}
        self.last_trigger: Optional[Dict[str, Any]] = None

    # -- recording (hot path) -------------------------------------------------
    def span(self, kind: str, seq: Optional[int], t0: float, t1: float,
             track: Optional[str] = None, **args) -> None:
        super().span(kind, seq, t0, t1, track, **args)
        if seq is None:
            return
        if kind in self._q:
            with self._fl_lock:
                d = self._frames.get(seq)
                if d is None:
                    if len(self._frames) >= _FRAMES_CAP:
                        self._prune_frames_locked()
                    d = self._frames[seq] = {}
                d[kind] = d.get(kind, 0.0) + (t1 - t0)
        if kind == "sink" and args and "e2e_s" in args:
            adm = args.get("e2e_adm_s")
            self._complete(seq, float(args["e2e_s"]),
                           float(adm) if adm is not None else None, t1)

    def mark(self, kind: str, seq: Optional[int],
             t: Optional[float] = None, track: Optional[str] = None,
             **args) -> None:
        if t is None:
            t = time.monotonic()
        super().mark(kind, seq, t, track, **args)
        # every fault-track mark is a trigger: injected/real faults
        # (``fault``), supervision outcomes (``fault_skip`` /
        # ``fault_retry`` / ``fault_degrade``), watchdog trips. The
        # watchdog means the pipeline may be wedged — flush immediately
        # rather than waiting for post-window completions that may
        # never come.
        if track == "faults":
            detail = {"mark": kind}
            if args:
                detail.update(args)
            trig = "watchdog" if kind == "watchdog_trip" else "fault"
            self._trigger(trig, seq, t, detail,
                          immediate=(trig == "watchdog"))

    def _prune_frames_locked(self) -> None:
        # drop the oldest in-flight accumulators (shed/errored frames
        # never reach the sink, so the map needs a pressure valve)
        drop = max(len(self._frames) - _FRAMES_CAP + 1,
                   _FRAMES_CAP // 8)
        for s in sorted(self._frames)[:drop]:
            del self._frames[s]

    def frame_stages(self, seq: int) -> Dict[str, float]:
        """One frame's accumulated stage durations — O(1) from the
        in-flight accumulator (overrides the Timeline's ring scan); a
        frame that already completed is found in the attribution ring.
        This is the span-vector source a query server reads at result
        egress (obs/distributed)."""
        with self._fl_lock:
            d = self._frames.get(seq)
            if d is not None:
                return dict(d)
            for s, vec in reversed(self._vectors):
                if s == seq:
                    return {k: v for k, v in vec.items() if k != "e2e"}
        return {}

    # -- completion -----------------------------------------------------------
    def _complete(self, seq: int, e2e_s: float,
                  e2e_adm_s: Optional[float], t: float) -> None:
        with self._fl_lock:
            vec = self._frames.pop(seq, None) or {}
            vec["e2e"] = e2e_s
            self._vectors.append((seq, vec))
            self._completed += 1
            completed = self._completed
        for kind, dur in vec.items():
            if kind != "e2e" and kind in self._q:
                self._q[kind]["p50"].observe(dur)
                self._q[kind]["p99"].observe(dur)
        self._q["e2e"]["p50"].observe(e2e_s)
        self._q["e2e"]["p99"].observe(e2e_s)
        if e2e_adm_s is not None:
            self._q["e2e_admitted"]["p50"].observe(e2e_adm_s)
            self._q["e2e_admitted"]["p99"].observe(e2e_adm_s)
        med = self._q["e2e"]["p50"].quantile()
        with self._fl_lock:
            self._rolling_med = med

        budget = self.slo_budget_s
        if budget is not None and budget > 0:
            lat = e2e_adm_s if e2e_adm_s is not None else e2e_s
            breached = lat > budget
            self.burn_fast.add(t, breached)
            self.burn_slow.add(t, breached)
            if breached:
                self._trigger("deadline", seq, t,
                              {"e2e_ms": round(lat * 1e3, 3),
                               "budget_ms": round(budget * 1e3, 3)})
            self._maybe_warn_burn(t)
        if (completed >= self.min_samples and med is not None
                and med > 0 and e2e_s > self.tail_k * med):
            self._trigger("tail", seq, t,
                          {"e2e_ms": round(e2e_s * 1e3, 3),
                           "median_ms": round(med * 1e3, 3),
                           "k": self.tail_k})
        # a pending dump flushes once the post-offender window completed
        # (read under the lock: _trigger — possibly just called above —
        # installs _pending under it)
        with self._fl_lock:
            pending = self._pending
        if pending is not None and pending["seq"] is not None \
                and seq >= pending["seq"] + self.window_frames:
            self._flush()

    # -- triggers & dumps -----------------------------------------------------
    def _trigger(self, kind: str, seq: Optional[int], t: float,
                 detail: Dict[str, Any], immediate: bool = False) -> None:
        with self._fl_lock:
            self.trigger_counts[kind] = self.trigger_counts.get(kind, 0) + 1
            self.last_trigger = {"kind": kind, "seq": seq,
                                 "detail": detail}
            if self._pending is None:
                self._pending = {"kind": kind, "seq": seq, "t": t,
                                 "detail": detail}
        if immediate:
            self._flush()

    def _flush(self) -> None:
        """Write the pending dump if the rate limiter allows it."""
        with self._fl_lock:
            pending = self._pending
            if pending is None:
                return
            self._pending = None
            now = time.monotonic()
            if self._last_dump_mono is not None and \
                    now - self._last_dump_mono < self.min_interval_s:
                self.suppressed_dumps += 1
                return
            if not self.dump_dir:
                return
            self._last_dump_mono = now
            self.dump_count += 1
            n = self.dump_count
        try:
            path = self._write_dump(pending, n)
        except OSError:
            return  # an unwritable flight dir must not take down serving
        self.dump_paths.append(path)

    def _write_dump(self, pending: Dict[str, Any], n: int) -> str:
        seq = pending["seq"]
        lo = hi = None
        if seq is not None:
            lo, hi = seq - self.window_frames, seq + self.window_frames
        spans: List[Dict[str, Any]] = []
        for thread, kind, s, t0, t1, track, args in self._snapshot():
            in_window = (lo is None or
                         (s is not None and lo <= s <= hi) or
                         track == "faults")
            if not in_window:
                continue
            spans.append({
                "thread": thread, "kind": kind, "seq": s,
                "t0_ms": round((t0 - self.epoch) * 1e3, 3),
                "t1_ms": round((t1 - self.epoch) * 1e3, 3)
                if t1 is not None else None,
                "track": track, "args": args,
            })
        with self._fl_lock:
            frames = {
                str(s): {k: round(v * 1e3, 4) for k, v in vec.items()}
                for s, vec in self._vectors
                if lo is None or lo <= s <= hi
            }
        doc = {
            "trigger": {"kind": pending["kind"], "seq": seq,
                        "t_ms": round((pending["t"] - self.epoch) * 1e3, 3),
                        "detail": pending["detail"]},
            "window": {"frames_before": self.window_frames,
                       "frames_after": self.window_frames,
                       "seq_lo": lo, "seq_hi": hi},
            "pipeline": self.pipeline_name,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "frames_ms": frames,
            "spans": spans,
            "slo": self.slo_snapshot(),
            "attribution": self.attribution(),
        }
        os.makedirs(self.dump_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(
            self.dump_dir,
            f"flight-{stamp}-{n:03d}-{pending['kind']}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return path

    def flush_pending(self) -> None:
        """Force the pending dump out (pipeline stop / retirement): an
        offender near EOS must not lose its dump to the post-window
        completion wait."""
        self._flush()

    # -- burn-rate warning ----------------------------------------------------
    def burn_rates(self, now: Optional[float] = None
                   ) -> Tuple[float, float]:
        if now is None:
            now = time.monotonic()
        return self.burn_fast.rate(now), self.burn_slow.rate(now)

    def burn_overload(self, now: Optional[float] = None) -> bool:
        """True while BOTH burn windows exceed the warn threshold — the
        scheduler treats this as an overload signal."""
        if self.slo_budget_s is None:
            return False
        fast, slow = self.burn_rates(now)
        return fast > self.burn_warn_threshold and \
            slow > self.burn_warn_threshold

    def _maybe_warn_burn(self, now: float) -> None:
        if not self.burn_overload(now):
            return
        if self._last_warn_mono is not None and \
                now - self._last_warn_mono < 10.0:
            return
        self._last_warn_mono = now
        pipe = self._pipe_ref() if self._pipe_ref is not None else None
        if pipe is None:
            return
        fast, slow = self.burn_rates(now)
        pipe.post_warning(
            None, f"SLO burn rate high: fast={fast:.1f}x "
            f"slow={slow:.1f}x of error budget "
            f"(budget {self.slo_budget_s * 1e3:.0f} ms)")

    # -- snapshots ------------------------------------------------------------
    def slo_snapshot(self) -> Dict[str, Any]:
        """Stage/e2e streaming quantiles + burn rates — the
        ``metrics_snapshot()["slo"]`` section."""
        now = time.monotonic()
        stages: Dict[str, Any] = {}
        for name, qs in self._q.items():
            c = qs["p50"].count
            if c == 0:
                continue
            p50 = qs["p50"].quantile()
            p99 = qs["p99"].quantile()
            stages[name] = {
                "p50_ms": round((p50 or 0.0) * 1e3, 4),
                "p99_ms": round((p99 or 0.0) * 1e3, 4),
                "count": c,
            }
        out: Dict[str, Any] = {
            "stages": stages,
            "completed": self._completed,  # nns-lint: disable=NNS201 -- monotonic int; an export snapshot at worst reads one frame stale, never torn
        }
        if self.slo_budget_s is not None:
            fast, slow = self.burn_rates(now)
            out["burn"] = {
                "budget_ms": round(self.slo_budget_s * 1e3, 3),
                "fast": round(fast, 4),
                "slow": round(slow, 4),
                "warn_threshold": self.burn_warn_threshold,
                "overloaded": self.burn_overload(now),
            }
        if self.dump_count or self.suppressed_dumps or self.last_trigger:
            out["dumps"] = {
                "written": self.dump_count,
                "suppressed": self.suppressed_dumps,
                "paths": list(self.dump_paths),
                "last_trigger": self.last_trigger,
                "triggers": dict(self.trigger_counts),
            }
        return out

    def quantile_states(self) -> Dict[str, Dict[str, dict]]:
        """Serializable P² marker states per stage — what a replica's
        ``/metrics.json`` exposes so a FederatedMetrics aggregator can
        marker-merge fleet quantiles without ever shipping samples."""
        return {name: {w: q.snapshot() for w, q in qs.items()}
                for name, qs in self._q.items()
                if qs["p50"].count > 0}

    def attribution(self) -> Dict[str, Any]:
        """Continuous variance attribution over the completed-frame
        ring: per-stage MAD vs e2e MAD, dominant stage, and advisory
        scheduler hints."""
        with self._fl_lock:
            done = [vec for _, vec in self._vectors]
        base = {"frames": len(done), "e2e_mad_ms": 0.0,
                "stage_mad_ms": {}, "dominant_stage": None,
                "dominant_share": 0.0, "hints": {}}
        if len(done) < 8:
            return base

        def _mad(vals: List[float]) -> float:
            vals = sorted(vals)
            med = vals[len(vals) // 2]
            dev = sorted(abs(v - med) for v in vals)
            return dev[len(dev) // 2]

        stage_mad = {k: _mad([d.get(k, 0.0) for d in done]) * 1e3
                     for k in _timeline.STAGES}
        e2e_mad = _mad([d.get("e2e", 0.0) for d in done]) * 1e3
        dominant = max(stage_mad, key=lambda k: stage_mad[k])
        if stage_mad[dominant] <= 0.0:
            return base
        hints: Dict[str, Any] = {}
        if dominant in ("ingest", "lane_reorder"):
            # host-side ingest spread: more lanes absorb it
            hints["lanes_hint_delta"] = 1
        elif dominant == "fence_wait":
            # frames block on the dispatch window's own fence: the
            # inflight target is too high for the device's service rate
            hints["inflight_pressure"] = True
        elif dominant in ("sched_hold", "queue_wait"):
            # spread accumulates while parked pre-dispatch: batches form
            # too slowly / too large for the arrival pattern
            hints["batch_cap_pressure"] = True
        base.update({
            "e2e_mad_ms": round(e2e_mad, 4),
            "stage_mad_ms": {k: round(v, 4)
                             for k, v in stage_mad.items()},
            "dominant_stage": dominant,
            "dominant_share": round(stage_mad[dominant] / e2e_mad, 4)
            if e2e_mad > 0 else 0.0,
            "hints": hints,
        })
        return base

    # -- serving continuity ---------------------------------------------------
    def checkpoint_state(self) -> Dict[str, Any]:
        """Durable recorder state for ``Pipeline.checkpoint()``: the P²
        marker sets (so stage/e2e quantile gauges resume warm), the
        completed-frame attribution ring, and the completion count.
        Burn-rate windows are NOT included — their events are anchored
        to this process's monotonic clock and a restored breach history
        would fire stale overload signals in the new process."""
        with self._fl_lock:
            vectors = list(self._vectors)
            completed = self._completed
            rolling_med = self._rolling_med
        return {
            "quantiles": {name: {w: q.snapshot() for w, q in qs.items()}
                          for name, qs in self._q.items()},
            "vectors": vectors,
            "completed": completed,
            "rolling_med": rolling_med,
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        for name, pair in (state.get("quantiles") or {}).items():
            qs = self._q.get(name)
            if qs is None:
                continue
            for w, qstate in pair.items():
                q = qs.get(w)
                if q is not None:
                    q.restore(qstate)
        med = state.get("rolling_med")
        with self._fl_lock:
            self._vectors.extend(state.get("vectors") or ())
            self._completed = int(state.get("completed", 0))
            self._rolling_med = float(med) if med is not None else None

    # -- gauges ---------------------------------------------------------------
    def register_gauges(self) -> None:
        """Export the streaming quantiles and burn rates through the
        process registry (both Prometheus text and the JSON snapshot go
        through ``collect()``, so one registration serves both)."""
        reg = get_registry()
        ref = weakref.ref(self)

        def _q_fn(name: str, which: str):
            def read() -> float:
                fr = ref()
                if fr is None:
                    return 0.0
                v = fr._q[name][which].quantile()
                return (v or 0.0) * 1e3
            return read

        def _burn_fn(window: str):
            def read() -> float:
                fr = ref()
                if fr is None or fr.slo_budget_s is None:
                    return 0.0
                fast, slow = fr.burn_rates()
                return fast if window == "fast" else slow
            return read

        labels = {"pipeline": self.pipeline_name}
        for name in _timeline.STAGES + ("e2e", "e2e_admitted"):
            reg.gauge("nns_stage_p50_ms",
                      "Streaming P2 median of per-frame stage seconds "
                      "(flight recorder)",
                      fn=_q_fn(name, "p50"), stage=name, **labels)
            reg.gauge("nns_stage_p99_ms",
                      "Streaming P2 p99 of per-frame stage seconds "
                      "(flight recorder)",
                      fn=_q_fn(name, "p99"), stage=name, **labels)
        for window in ("fast", "slow"):
            reg.gauge("nns_slo_burn_rate",
                      "SLO error-budget burn rate over the fast/slow "
                      "alerting window (1.0 = sustainable)",
                      fn=_burn_fn(window), window=window, **labels)


def maybe_install(pipeline) -> Optional[FlightRecorder]:
    """``Pipeline.start()`` hook: install the always-on recorder as the
    process-wide ledger unless tracing already claimed the slot or
    ``NNSTPU_FLIGHT`` says no. Returns the installed recorder."""
    if not flight_enabled():
        return None
    if _timeline.ACTIVE is not None:
        # an explicit or NNSTPU_TRACE timeline wins: it records the
        # same spans at full capacity, and the flight machinery would
        # only double the hot-path work
        return None
    budget_s: Optional[float] = None
    sched = getattr(pipeline, "_slo_scheduler", None)
    if sched is not None:
        budget_s = getattr(sched, "budget_s", None)
    elif getattr(pipeline, "slo_budget_ms", 0.0) > 0:
        budget_s = pipeline.slo_budget_ms / 1e3
    fr = FlightRecorder(
        capacity=int(_env_float("NNSTPU_FLIGHT_CAPACITY", 4096)),
        dump_dir=getattr(pipeline, "flight_dir", None) or env_dump_dir(),
        slo_budget_s=budget_s,
        pipeline=pipeline)
    fr._env_owned = False
    _timeline.ACTIVE = fr
    fr.register_gauges()
    return fr


def retire(fr: Optional[FlightRecorder]) -> None:
    """``Pipeline.stop()`` hook: flush any pending dump and release the
    process-wide slot (the recorder object stays readable — the post-EOS
    footer and bench harvest its snapshots after stop)."""
    if fr is None:
        return
    fr.flush_pending()
    if _timeline.ACTIVE is fr:
        _timeline.ACTIVE = None


class LMTokenStats:
    """Per-token serving-latency quantiles for ONE decode engine —
    the flight recorder's LM-serving split: time-to-first-token (queue
    wait + prefill + first sample, the interactive-feel number) tracked
    separately from the steady-state inter-token interval (decode
    throughput per stream). Four P² estimators, no sample storage,
    exported as ``nns_lm_ttft_p50/p99_ms`` and
    ``nns_lm_token_p50/p99_ms`` gauges labeled by engine.

    Gauges read through a weakref so a dropped engine (and its stats)
    unregisters cleanly instead of pinning itself via the registry.
    """

    def __init__(self, engine: str):
        self._q = {
            "ttft": {"p50": P2Quantile(0.5), "p99": P2Quantile(0.99)},
            "token": {"p50": P2Quantile(0.5), "p99": P2Quantile(0.99)},
        }
        reg = get_registry()
        ref = weakref.ref(self)

        def _q_fn(name, which):
            def read():
                st = ref()
                if st is None:
                    return 0.0
                v = st._q[name][which].quantile()
                return (v or 0.0) * 1e3

            return read

        for which in ("p50", "p99"):
            reg.gauge(f"nns_lm_ttft_{which}_ms",
                      "time-to-first-token (submit -> first emitted "
                      "token), streaming quantile",
                      fn=_q_fn("ttft", which), engine=engine)
            reg.gauge(f"nns_lm_token_{which}_ms",
                      "steady-state inter-token interval per stream, "
                      "streaming quantile",
                      fn=_q_fn("token", which), engine=engine)

    def observe_ttft(self, seconds: float) -> None:
        self._q["ttft"]["p50"].observe(seconds)
        self._q["ttft"]["p99"].observe(seconds)

    def observe_token(self, seconds: float) -> None:
        self._q["token"]["p50"].observe(seconds)
        self._q["token"]["p99"].observe(seconds)
