"""MetricsServer — HTTP telemetry export (Prometheus text + JSON).

Endpoints:

- ``/metrics``       Prometheus text exposition (format 0.0.4) — point a
  Prometheus scrape job (or ``curl``) at it.
- ``/metrics.json``  the same registry as a structured JSON snapshot
  (histograms include per-bucket counts and p50/p99 estimates).
- ``/healthz``       liveness probe (200 ``ok``).

The server is a stdlib ``ThreadingHTTPServer`` on a daemon thread — no
new dependencies, safe to run alongside a PLAYING pipeline (scrapes only
take short per-metric locks).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs.registry import MetricsRegistry, get_registry

log = get_logger("obs")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set on the subclass by MetricsServer
    #: optional extra-sections provider (``slo`` / ``attribution`` /
    #: ``quantiles`` from the pipeline's flight recorder) merged into
    #: the /metrics.json snapshot — the in-process
    #: ``metrics_snapshot()`` parity the footer readers asked for, and
    #: what fleet federation scrapes
    snapshot_fn = None
    #: optional FederatedMetrics serving the /fleet/* routes
    federation = None

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = self.registry.render_prometheus().encode()
            ctype = PROMETHEUS_CONTENT_TYPE
        elif path == "/metrics.json":
            snap = self.registry.snapshot()
            fn = type(self).snapshot_fn
            if fn is not None:
                try:
                    extra = fn() or {}
                except Exception as e:  # noqa: BLE001 — a scrape must
                    # never 500 because the pipeline is mid-transition
                    log.debug("metrics snapshot sections failed: %s", e)
                    extra = {}
                for key in ("slo", "attribution", "quantiles"):
                    if key in extra:
                        snap[key] = extra[key]
            body = json.dumps(snap).encode()
            ctype = "application/json"
        elif path == "/fleet/metrics" and self.federation is not None:
            body = self.federation.render_prometheus().encode()
            ctype = PROMETHEUS_CONTENT_TYPE
        elif path == "/fleet/metrics.json" and self.federation is not None:
            body = json.dumps(self.federation.collect()).encode()
            ctype = "application/json"
        elif path == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        else:
            self.send_error(404, "unknown path (try /metrics)")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        log.debug("metrics http: " + fmt, *args)


class MetricsServer:
    """Serve a registry over HTTP; ``port=0`` binds an ephemeral port
    (resolved into :attr:`port` after :meth:`start`)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 snapshot_fn=None, federation=None):
        self.registry = registry or get_registry()
        self.host = host
        self.port = int(port)
        #: callable returning extra /metrics.json sections
        #: (slo/attribution/quantiles) — see _Handler.snapshot_fn
        self.snapshot_fn = snapshot_fn
        #: FederatedMetrics aggregator backing /fleet/metrics[.json]
        self.federation = federation
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        handler = type("BoundHandler", (_Handler,),
                       {"registry": self.registry,
                        "snapshot_fn": staticmethod(self.snapshot_fn)
                        if self.snapshot_fn is not None else None,
                        "federation": self.federation})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="metrics-http", daemon=True)
        self._thread.start()
        log.info("metrics server on http://%s:%d/metrics", self.host,
                 self.port)
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
