"""Runtime lock-order witness — the dynamic half of the NNS202 story.

The static analyzer (``analysis/concurrency.py``) derives a lock-order
graph from the code; this module records the orders the process
*actually* takes. With ``NNSTPU_LOCKGRAPH=1`` the ``threading.Lock`` /
``threading.RLock`` factories are replaced by ones that, **only for
locks created from nnstreamer_tpu code** (creator-frame filtered),
return an instrumented wrapper that

- records per-thread acquisition stacks and every held→acquired edge
  into one process-wide digraph, keyed by the lock's creation site
  (``relpath:lineno`` — the same key the static graph's ``sites`` map
  translates to symbolic names);
- detects cycles online at edge insertion (a cycle = two threads have
  taken these locks in opposite orders = a potential deadlock that the
  interleaving happened not to trigger this run);
- dumps the observed graph as JSON (``NNSTPU_LOCKGRAPH=<path>`` dumps
  at exit), so CI can assert acyclicity and cross-check against the
  static NNS202 graph with :func:`cross_check` — each view validating
  the other is the point: the static graph proves paths the test run
  never exercised, the runtime graph proves orders the analyzer's
  heuristics could not see.

With ``NNSTPU_LOCKGRAPH`` unset (the default) importing this module
changes nothing: the factories are untouched and every lock in the
process is a plain ``threading.Lock`` — a byte-identical no-op, same as
the fault-injection and flight-recorder kill switches.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

#: the REAL factories, bound at import time — the witness's own state
#: must never be guarded by an instrumented lock (infinite recursion),
#: and deactivate() must restore exactly these
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: filesystem root of the package: locks created by files under this
#: root are instrumented, everything else (stdlib, site-packages, test
#: files) gets a real lock untouched
_PKG_ROOT = str(Path(__file__).resolve().parent.parent)
_REL_BASE = str(Path(_PKG_ROOT).parent)

ENV = "NNSTPU_LOCKGRAPH"


class LockGraph:
    """Process-wide observed acquisition-order digraph.

    Nodes are lock creation sites (``relpath:lineno``); an edge a→b
    means some thread acquired b while holding a. ``violations``
    collects every cycle the moment its closing edge is inserted."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self.nodes: Dict[str, str] = {}            # site -> kind
        self.edges: Dict[Tuple[str, str], int] = {}
        self._adj: Dict[str, Set[str]] = {}
        self.acquisitions = 0
        self.violations: List[Dict[str, Any]] = []

    # -- per-thread stack ---------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_created(self, site: str, kind: str) -> None:
        with self._mu:
            self.nodes.setdefault(site, kind)

    def note_acquired(self, site: str) -> None:
        st = self._stack()
        with self._mu:
            self.acquisitions += 1
            if site not in st:       # reentrant re-acquire adds no order
                for held in st:
                    self._add_edge(held, site)
        st.append(site)

    def note_released(self, site: str) -> None:
        st = self._stack()
        # pop the innermost occurrence: releases may legally interleave
        # (lock A, lock B, release A, release B)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == site:
                del st[i]
                return

    # -- graph --------------------------------------------------------------
    def _add_edge(self, a: str, b: str) -> None:
        """Caller holds ``self._mu``. Insert a→b; if b can already reach
        a, this edge closes a cycle — record it as a violation."""
        if a == b:
            if self.nodes.get(a) != "rlock":
                self.edges[(a, b)] = self.edges.get((a, b), 0) + 1
                self.violations.append({
                    "cycle": [a, a],
                    "thread": threading.current_thread().name,
                    "edge": [a, b],
                })
            return
        is_new = (a, b) not in self.edges
        self.edges[(a, b)] = self.edges.get((a, b), 0) + 1
        if not is_new:
            return
        self._adj.setdefault(a, set()).add(b)
        self._adj.setdefault(b, set())
        path = self._find_path(b, a)
        if path is not None:
            self.violations.append({
                "cycle": path + [b],
                "thread": threading.current_thread().name,
                "edge": [a, b],
            })

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Iterative DFS src→dst over ``_adj``; returns the node path or
        None. Caller holds ``self._mu``."""
        if src == dst:
            return [src]
        parent: Dict[str, str] = {src: src}
        work = [src]
        while work:
            n = work.pop()
            for m in self._adj.get(n, ()):
                if m in parent:
                    continue
                parent[m] = n
                if m == dst:
                    path = [m]
                    while path[-1] != src:
                        path.append(parent[path[-1]])
                    return list(reversed(path))
                work.append(m)
        return None

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "version": 1,
                "nodes": dict(self.nodes),
                "edges": [{"from": a, "to": b, "count": n}
                          for (a, b), n in sorted(self.edges.items())],
                "acquisitions": self.acquisitions,
                "violations": [dict(v) for v in self.violations],
            }


class _InstrumentedLock:
    """Wraps a real lock; reports acquire/release to the graph.

    Unknown attributes delegate to the inner lock, which keeps
    ``threading.Condition`` working either way: wrapping an RLock,
    Condition finds the real ``_release_save``/``_acquire_restore`` and
    bypasses the wrapper symmetrically across ``wait()`` (held stack
    correctly unchanged); wrapping a Lock, the delegation raises
    AttributeError and Condition falls back to ``acquire``/``release``,
    which do report."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _GRAPH.note_acquired(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        _GRAPH.note_released(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self._site} of {self._inner!r}>"

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


_GRAPH = LockGraph()
_active = False
_dump_path: Optional[str] = None


def _creation_site() -> Optional[str]:
    """``relpath:lineno`` of the frame calling the lock factory, or
    None when that frame is not nnstreamer_tpu code (stdlib internals —
    queue.Queue's mutex, Condition's default RLock — stay real)."""
    try:
        frame = sys._getframe(2)
    except ValueError:          # pragma: no cover — no caller frame
        return None
    fn = frame.f_code.co_filename
    if not fn.startswith(_PKG_ROOT) or fn == __file__:
        return None
    rel = os.path.relpath(fn, _REL_BASE)
    return f"{rel.replace(os.sep, '/')}:{frame.f_lineno}"


def _lock_factory():
    site = _creation_site()
    inner = _REAL_LOCK()
    if site is None:
        return inner
    _GRAPH.note_created(site, "lock")
    return _InstrumentedLock(inner, site)


def _rlock_factory():
    site = _creation_site()
    inner = _REAL_RLOCK()
    if site is None:
        return inner
    _GRAPH.note_created(site, "rlock")
    return _InstrumentedLock(inner, site)


def is_active() -> bool:
    return _active


def graph() -> LockGraph:
    return _GRAPH


def activate() -> None:
    """Patch the ``threading`` lock factories. Idempotent. Locks created
    BEFORE activation stay real — arm before importing modules whose
    import creates locks (the package ``__init__`` does this when the
    env var is set, ahead of every other import)."""
    global _active
    if _active:
        return
    _active = True
    threading.Lock = _lock_factory          # type: ignore[assignment]
    threading.RLock = _rlock_factory        # type: ignore[assignment]


def deactivate() -> LockGraph:
    """Restore the real factories; existing instrumented locks keep
    working (they hold real locks inside). Returns the graph."""
    global _active
    threading.Lock = _REAL_LOCK             # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK           # type: ignore[assignment]
    _active = False
    return _GRAPH


def reset() -> None:
    """Fresh graph (tests): forget nodes, edges, and violations."""
    global _GRAPH
    _GRAPH = LockGraph()


def snapshot() -> Dict[str, Any]:
    return _GRAPH.snapshot()


def dump(path: str) -> str:
    """Write the observed graph as JSON (atomic tmp+rename)."""
    snap = snapshot()
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(snap, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def _dump_atexit() -> None:     # pragma: no cover — exercised via CI
    if _dump_path:
        try:
            dump(_dump_path)
        except OSError as e:
            # lazy import: lockgraph must import nothing that creates
            # locks (it runs before every other nnstreamer_tpu import)
            from nnstreamer_tpu.log import get_logger
            get_logger("obs.lockgraph").warning(
                "lockgraph: dump to %s failed: %s", _dump_path, e)


def maybe_activate_env() -> bool:
    """Arm from ``NNSTPU_LOCKGRAPH``: unset/``0`` → do nothing (the
    byte-identical default), ``1`` → record in-process, any other value
    → record AND dump the JSON graph to that path at exit."""
    global _dump_path
    val = os.environ.get(ENV, "").strip()
    if val in ("", "0"):
        return False
    if val != "1" and _dump_path is None:
        _dump_path = val
        atexit.register(_dump_atexit)
    activate()
    return True


def cross_check(runtime: Dict[str, Any],
                static: Dict[str, Any]) -> List[str]:
    """Validate the observed graph against the static NNS202 graph.

    Translates runtime creation-site nodes to the static graph's
    symbolic names through its ``sites`` map, unions both edge sets,
    and reports:

    - every runtime-observed cycle (``violations``);
    - any cycle in the union graph — a static order A→B combined with
      an observed order B→A is a deadlock neither view sees alone.

    Returns a list of human-readable contradictions; empty = the two
    views agree on an acyclic order."""
    sites: Dict[str, str] = static.get("sites", {})
    problems: List[str] = []
    for v in runtime.get("violations", []):
        cyc = " -> ".join(sites.get(s, s) for s in v["cycle"])
        problems.append(f"observed lock-order cycle on thread "
                        f"{v['thread']}: {cyc}")

    adj: Dict[str, Set[str]] = {}

    def add(a: str, b: str) -> None:
        if a != b:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())

    for e in static.get("edges", []):
        add(e["from"], e["to"])
    for e in runtime.get("edges", []):
        add(sites.get(e["from"], e["from"]), sites.get(e["to"], e["to"]))

    # cycle scan (iterative coloring) over the union graph
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    for root in sorted(adj):
        if color[root] != WHITE:
            continue
        stack: List[Tuple[str, Any]] = [(root, iter(sorted(adj[root])))]
        color[root] = GREY
        trail = [root]
        while stack:
            node, it = stack[-1]
            for child in it:
                if color[child] == GREY:
                    i = trail.index(child)
                    cyc = " -> ".join(trail[i:] + [child])
                    problems.append(
                        f"static/runtime contradiction: the union of "
                        f"the two graphs is cyclic: {cyc}")
                    continue
                if color[child] == WHITE:
                    color[child] = GREY
                    trail.append(child)
                    stack.append((child, iter(sorted(adj[child]))))
                    break
            else:
                color[node] = BLACK
                trail.pop()
                stack.pop()
    return problems
