"""Distributed observability plane — cross-hop trace propagation and
fleet metrics federation.

Every observability surface built so far (metrics registry, frame-ledger
Timeline, flight recorder, P² SLO quantiles, MAD variance attribution)
is per-process: the moment a frame crosses a ``tensor_query`` / gRPC /
MQTT hop its ledger goes dark and the remote half of its latency is one
unattributed blob. This module makes one frame's ledger span the whole
edge-cloud graph, in two halves:

Trace-context propagation
-------------------------
The reliable query wire (PR-11 TRANSFER_EX / RESULT_EX) grows an EX2
variant carrying a u64 trace id + wall-clock send stamp outbound and a
compact per-frame *span blob* (stage→seconds durations, remote total,
endpoint name) inbound, negotiated through a ``dt1`` HELLO feature token
so a pre-16 peer keeps every wire byte identical. The client splices the
remote vector into its own ledger as the :data:`~.timeline.DIST_STAGES`
(``hop_send`` / ``remote_queue`` / ``remote_device`` / ``remote_other``
/ ``hop_recv``).

**Skew anchoring rule**: remote spans are *durations*, anchored strictly
inside the client's observed ``[sent_t, recv_t]`` monotonic RTT window —
raw remote clocks are never compared against local ones. The only use of
wall stamps is to split the residual wire time into its send/receive
halves, and only when that split lands inside the window (clocks sane);
otherwise the split falls back to symmetric halves. When
``NNSTPU_NTP_SERVERS`` is set both peers pre-correct their wall stamps
via ``query/ntp.py``, tightening the split without changing the rule.

Because the spliced kinds are members of ``timeline.STAGES``, the flight
recorder's stage vectors, P² gauges, MAD variance attribution, and
forensic dumps name *remote* stages with zero extra wiring — a tail dump
can finally say "the p99.9 frame spent 310 ms in remote_device on
endpoint B".

Fleet metrics federation
------------------------
:class:`FederatedMetrics` scrapes N replica ``/metrics.json`` endpoints
(static list, or discovered via ``query/discovery.py`` metrics-port
advertisements), merging counters by sum, gauges by labeled instance,
and P² quantile marker states via :func:`~.quantiles.merge_p2_snapshots`
— replicas ship five-marker states, never raw samples. The merged view
is exposed as ``/fleet/metrics`` (Prometheus text, ``nns_fleet_*``
names) and ``/fleet/metrics.json`` on the MetricsServer, including
per-endpoint SLO burn-rate windows — the signal the ROADMAP's
join-shortest-slack fleet balancer will consume.

Kill switch: ``NNSTPU_DIST_TRACE=0`` (or false/no/off) disables the
feature offer entirely — no ``dt1`` token, no EX2 commands, byte-
identical wire vs the pre-distributed build. Talking to a peer that does
not echo the token has the same effect per connection.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.obs.quantiles import merge_p2_snapshots

log = get_logger("obs.distributed")

_ENV = "NNSTPU_DIST_TRACE"
_FALSY = ("0", "false", "no", "off")

#: HELLO feature token: both peers must speak it before EX2 is used
FEATURE = "dt1"

#: remote stage kinds folded into each spliced client-side stage
REMOTE_QUEUE_KINDS = ("ingest", "lane_reorder", "queue_wait",
                      "sched_hold", "fence_wait")
REMOTE_DEVICE_KINDS = ("device", "d2h")


def enabled() -> bool:
    """True unless ``NNSTPU_DIST_TRACE`` is an explicit falsy spelling —
    like the flight recorder, the distributed plane is on by default and
    *negotiated* per connection, so the off path costs nothing."""
    v = os.environ.get(_ENV, "").strip()
    return not (v and v.lower() in _FALSY)


# -- HELLO feature negotiation ----------------------------------------------
def hello_offer() -> str:
    """Suffix the client appends to its ``instance:window`` HELLO
    payload: ``:dt1`` when armed, empty (classic bytes) when not."""
    return f":{FEATURE}" if enabled() else ""


def parse_features(text: str) -> frozenset:
    """Feature tokens from the tail of a HELLO payload or reply."""
    return frozenset(t for t in text.split(":") if t and not t.isdigit())


def hello_accepts(reply: bytes) -> bool:
    """Did the server's HELLO echo grant the dt1 feature?"""
    try:
        return FEATURE in parse_features(reply.decode())
    except UnicodeDecodeError:
        return False


# -- wall-clock stamps -------------------------------------------------------
_ntp_lock = threading.Lock()
_ntp_offset_s: Optional[float] = None


def wall_offset_s() -> float:
    """Best-effort local wall-clock correction (seconds to add) from
    ``query/ntp.py`` when ``NNSTPU_NTP_SERVERS`` names servers; 0.0
    otherwise. Measured once, cached — stamps stay cheap."""
    global _ntp_offset_s
    with _ntp_lock:
        if _ntp_offset_s is not None:
            return _ntp_offset_s
        spec = os.environ.get("NNSTPU_NTP_SERVERS", "").strip()
        if not spec:
            _ntp_offset_s = 0.0
            return 0.0
        try:
            from nnstreamer_tpu.query import ntp

            servers = []
            for item in spec.split(","):
                h, _, p = item.strip().partition(":")
                servers.append((h, int(p) if p else 123))
            _ntp_offset_s = (ntp.corrected_epoch_ns(tuple(servers))
                             - time.time_ns()) / 1e9
        except (OSError, ValueError) as e:
            log.warning("ntp correction unavailable (%s); wall stamps "
                        "stay uncorrected", e)
            _ntp_offset_s = 0.0
        return _ntp_offset_s


def wall_now() -> float:
    """Epoch seconds, NTP-corrected when configured — what goes on the
    wire as an advisory stamp."""
    wall = time.time()
    return wall + wall_offset_s()


# -- span blobs --------------------------------------------------------------
def pack_span_blob(stages: Dict[str, float], total_s: float,
                   recv_wall: float, send_wall: float,
                   endpoint: str) -> bytes:
    """The compact per-frame span vector a server piggybacks on
    RESULT_EX2: durations only (skew-safe), plus advisory wall stamps."""
    return json.dumps({
        "v": 1,
        "total": round(total_s, 9),
        "stages": {k: round(v, 9) for k, v in stages.items() if v > 0.0},
        "recv_wall": recv_wall,
        "send_wall": send_wall,
        "endpoint": endpoint,
    }).encode()


def unpack_span_blob(blob: bytes) -> Dict[str, Any]:
    if not blob:
        return {}
    try:
        doc = json.loads(blob.decode())
        return doc if isinstance(doc, dict) else {}
    except (ValueError, UnicodeDecodeError):
        return {}


def collect_frame_stages(seq: Optional[int]) -> Dict[str, float]:
    """Per-frame stage durations from the process-wide ledger — O(1)
    from a flight recorder's accumulator, a bounded scan from a plain
    Timeline, empty when no ledger is installed."""
    tl = _timeline.ACTIVE
    if tl is None or seq is None:
        return {}
    return tl.frame_stages(seq)


# -- trace meta for non-query hops (gRPC / MQTT payload headers) -------------
TRACE_ID_META = "dist_trace_id"
SENT_WALL_META = "dist_sent_wall"


def attach_trace_meta(meta: Dict[str, Any],
                      seq: Optional[int] = None) -> Dict[str, Any]:
    """Stamp outbound trace context into a payload-meta dict (the gRPC
    flex codec / MQTT header carriers). No-op when disarmed."""
    if enabled():
        if seq is None:
            seq = meta.get(_timeline.TRACE_SEQ_META)
        meta[TRACE_ID_META] = int(seq) if seq is not None else 0
        meta[SENT_WALL_META] = wall_now()
    return meta


def extract_trace_meta(meta: Dict[str, Any]
                       ) -> Optional[Tuple[int, float]]:
    """(trace_id, sent_wall) from an inbound meta dict, or None."""
    tid = meta.get(TRACE_ID_META)
    if tid is None:
        return None
    try:
        return int(tid), float(meta.get(SENT_WALL_META, 0.0))
    except (TypeError, ValueError):
        return None


# -- the splice --------------------------------------------------------------
def splice_remote(tl, seq: Optional[int], sent_t: float, recv_t: float,
                  sent_wall: float, span: Dict[str, Any]) -> None:
    """Splice a remote span blob into the client ledger as the five
    DIST_STAGES, anchored sequentially inside ``[sent_t, recv_t]`` (the
    client's own monotonic RTT window — see the skew-anchoring rule in
    the module docstring).

    ``tl`` is the client's active Timeline/FlightRecorder; ``seq`` the
    client frame's trace seq. Remote stage durations are clamped (scaled
    down proportionally if the remote ledger over-reports) so the five
    spans always tile the window exactly.
    """
    if tl is None or seq is None:
        return
    rtt = recv_t - sent_t
    if rtt <= 0.0:
        return
    endpoint = str(span.get("endpoint") or "remote")
    total = float(span.get("total") or 0.0)
    total = min(max(total, 0.0), rtt)
    wire = rtt - total

    # wall-stamp split of the wire time into its outbound/inbound halves,
    # used only when it lands inside the window; symmetric otherwise
    hop_send = wire / 2.0
    recv_wall = span.get("recv_wall")
    if recv_wall and sent_wall:
        fwd = float(recv_wall) - float(sent_wall)
        if 0.0 <= fwd <= wire:
            hop_send = fwd
    hop_recv = wire - hop_send

    stages = span.get("stages") or {}
    queue = sum(float(stages.get(k, 0.0)) for k in REMOTE_QUEUE_KINDS)
    device = sum(float(stages.get(k, 0.0)) for k in REMOTE_DEVICE_KINDS)
    known = queue + device
    if known > total > 0.0:
        scale = total / known
        queue *= scale
        device *= scale
        known = total
    elif known > 0.0 and total <= 0.0:
        queue = device = known = 0.0
    other = max(total - known, 0.0)

    # hop spans are the LOCAL view of the wire (they stay on this
    # process's "net" track); the remote_* spans carry the endpoint arg,
    # which the Chrome exporter renders as that endpoint's own process
    t = sent_t
    tl.span("hop_send", seq, t, t + hop_send, track="net", peer=endpoint)
    t += hop_send
    for kind, dur in (("remote_queue", queue),
                      ("remote_device", device),
                      ("remote_other", other)):
        tl.span(kind, seq, t, t + dur, track="remote",
                endpoint=endpoint)
        t += dur
    tl.span("hop_recv", seq, t, recv_t, track="net", peer=endpoint)


# -- fleet metrics federation ------------------------------------------------
class FederatedMetrics:
    """Scrape-and-merge aggregator over N replica ``/metrics.json``
    endpoints.

    Merge rules (the federation contract, see docs/distributed.md):

    - **counters** sum across replicas per (name, labels) series;
    - **gauges** keep one sample per replica, labeled
      ``instance="host:port"`` (averaging a gauge lies);
    - **P² quantile states** (the ``quantiles`` section each replica
      exposes) merge via the marker-merge path into fleet-level
      p50/p99 per stage;
    - **burn-rate windows** stay per endpoint — a fleet-average burn
      rate would hide a single replica on fire.

    ``endpoints`` is a list of ``(host, port)`` metrics addresses;
    alternatively pass ``operation`` (+ broker coordinates) to discover
    replicas that advertise a ``metrics_port`` through
    ``query/discovery.py``.
    """

    def __init__(self, endpoints: Optional[List[Tuple[str, int]]] = None,
                 operation: Optional[str] = None,
                 broker_host: str = "127.0.0.1", broker_port: int = 1883,
                 timeout: float = 2.0):
        self.endpoints: List[Tuple[str, int]] = list(endpoints or [])
        self.operation = operation
        self.broker_host = broker_host
        self.broker_port = broker_port
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        #: "host:port" → last scrape result / error witness
        self._last: Dict[str, Dict[str, Any]] = {}

    # -- discovery -----------------------------------------------------------
    def discover(self, timeout: float = 5.0) -> List[Tuple[str, int]]:
        """Refresh the endpoint list from broker discovery (replicas
        advertising a ``metrics_port``); static endpoints are kept."""
        if not self.operation:
            return self.endpoints
        from nnstreamer_tpu.query.discovery import ServerDiscovery

        disco = ServerDiscovery(self.broker_host, self.broker_port,
                                str(self.operation))
        try:
            disco.wait_servers(timeout=timeout)
            found = disco.metrics_endpoints()
        finally:
            disco.close()
        merged = dict.fromkeys(self.endpoints)
        merged.update(dict.fromkeys(found))
        self.endpoints = list(merged)
        return self.endpoints

    # -- scraping ------------------------------------------------------------
    def scrape_one(self, host: str, port: int) -> Optional[Dict[str, Any]]:
        url = f"http://{host}:{port}/metrics.json"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except (OSError, ValueError) as e:
            log.warning("federation scrape of %s failed: %s", url, e)
            return None

    def collect(self) -> Dict[str, Any]:
        """Scrape every endpoint and return the merged fleet view."""
        wall_ts = time.time()
        counters: Dict[Tuple[str, tuple], float] = {}
        counter_help: Dict[str, str] = {}
        gauges: List[Dict[str, Any]] = []
        quantile_states: Dict[str, Dict[str, List[dict]]] = {}
        burn: Dict[str, Any] = {}
        endpoints: Dict[str, Dict[str, Any]] = {}
        for host, port in list(self.endpoints):
            inst = f"{host}:{port}"
            snap = self.scrape_one(host, port)
            endpoints[inst] = {"ok": snap is not None, "ts": wall_ts}
            if snap is None:
                continue
            for m in snap.get("metrics", ()):
                name = m.get("name")
                if not name:
                    continue
                labels = m.get("labels") or {}
                if m.get("type") == "counter":
                    key = (name, tuple(sorted(labels.items())))
                    counters[key] = counters.get(key, 0.0) + \
                        float(m.get("value", 0.0))
                    counter_help.setdefault(name, m.get("help", ""))
                elif m.get("type") == "gauge":
                    gauges.append({"name": name,
                                   "labels": {**labels, "instance": inst},
                                   "value": float(m.get("value", 0.0))})
            for stage, pair in (snap.get("quantiles") or {}).items():
                slot = quantile_states.setdefault(
                    stage, {"p50": [], "p99": []})
                for w in ("p50", "p99"):
                    state = pair.get(w)
                    if state:
                        slot[w].append(state)
            b = (snap.get("slo") or {}).get("burn")
            if b:
                burn[inst] = b
        quantiles: Dict[str, Any] = {}
        for stage, states in quantile_states.items():
            p50 = merge_p2_snapshots(states["p50"], 0.5)
            p99 = merge_p2_snapshots(states["p99"], 0.99)
            if p50 is None and p99 is None:
                continue
            quantiles[stage] = {
                "p50_ms": round((p50 or 0.0) * 1e3, 4),
                "p99_ms": round((p99 or 0.0) * 1e3, 4),
                "count": sum(int(s.get("count", 0))
                             for s in states["p50"]),
            }
        out = {
            "ts": wall_ts,
            "endpoints": endpoints,
            "counters": [
                {"name": name, "labels": dict(labels), "value": value}
                for (name, labels), value in sorted(counters.items())
            ],
            "gauges": gauges,
            "quantiles": quantiles,
            "burn": burn,
        }
        with self._lock:
            self._last = endpoints
        return out

    # -- rendering -----------------------------------------------------------
    @staticmethod
    def _labels(labels: Dict[str, Any]) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return "{" + inner + "}"

    def render_prometheus(self) -> str:
        """The ``nns_fleet_*`` text view of :meth:`collect`."""
        view = self.collect()
        lines: List[str] = []
        up = view["endpoints"]
        lines.append("# TYPE nns_fleet_endpoint_up gauge")
        for inst, st in sorted(up.items()):
            lines.append(f'nns_fleet_endpoint_up'
                         f'{{instance="{inst}"}} '
                         f'{1 if st["ok"] else 0}')
        seen_counter = set()
        for c in view["counters"]:
            fleet = f"nns_fleet_{c['name']}"
            if fleet not in seen_counter:
                lines.append(f"# TYPE {fleet} counter")
                seen_counter.add(fleet)
            lines.append(f"{fleet}{self._labels(c['labels'])} "
                         f"{c['value']:g}")
        seen_gauge = set()
        for g in view["gauges"]:
            fleet = f"nns_fleet_{g['name']}"
            if fleet not in seen_gauge:
                lines.append(f"# TYPE {fleet} gauge")
                seen_gauge.add(fleet)
            lines.append(f"{fleet}{self._labels(g['labels'])} "
                         f"{g['value']:g}")
        for which in ("p50", "p99"):
            lines.append(f"# TYPE nns_fleet_stage_{which}_ms gauge")
            for stage, q in sorted(view["quantiles"].items()):
                lines.append(f'nns_fleet_stage_{which}_ms'
                             f'{{stage="{stage}"}} '
                             f'{q[f"{which}_ms"]:g}')
        lines.append("# TYPE nns_fleet_burn_rate gauge")
        for inst, b in sorted(view["burn"].items()):
            for window in ("fast", "slow"):
                if window in b:
                    lines.append(
                        f'nns_fleet_burn_rate{{instance="{inst}",'
                        f'window="{window}"}} {b[window]:g}')
        return "\n".join(lines) + "\n"
