"""Thread-safe metrics registry: Counter / Gauge / Histogram primitives.

Design notes (who calls what, and from which thread):

- Hot paths (element ``chain``, queue worker, serving loop) hold a
  reference to their metric object and call ``inc``/``set``/``observe``
  — one short per-metric lock, no registry lookup per frame.
- Collectors are callables run at scrape time (``collect()``); they pull
  values out of live objects (e.g. each element's ``InvokeStats``) so
  sampled gauges always agree with the in-band properties. A collector
  returning ``False`` is dropped — the weakref-to-pipeline pattern.
- One metric identity = (name, sorted labels). Re-requesting it returns
  the same object (get-or-create), so instrumentation code never needs
  to coordinate creation.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

#: default latency buckets (seconds) — spans µs-scale host hops to the
#: multi-second first-compile outliers a TPU pipeline actually produces
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    100e-6, 250e-6, 500e-6, 1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3,
    100e-3, 250e-3, 500e-3, 1.0, 2.5, 5.0, 10.0,
)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None
                ) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(items.items()))
    return "{" + body + "}"


class _Metric:
    KIND = "untyped"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = {str(k): str(v) for k, v in labels.items()}
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonically increasing count."""

    KIND = "counter"

    def __init__(self, name: str, labels: Dict[str, str]):
        super().__init__(name, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (got {n})")
        with self._lock:
            self._value += n

    def set_total(self, value: float) -> None:
        """Collector-side absolute update from an external monotonic
        source (e.g. ``InvokeStats.total_invokes``); never decreases."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value; optionally backed by a callable sampled at
    collection time (``fn``)."""

    KIND = "gauge"

    def __init__(self, name: str, labels: Dict[str, str],
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, labels)
        self._value = 0.0
        self.fn = fn

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:  # noqa: BLE001 — a dead callback reads 0,
                # it must not poison the whole scrape
                return 0.0
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus cumulative-bucket semantics).

    Buckets are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail. ``percentile(q)`` interpolates linearly inside the
    winning bucket — the same estimate a PromQL ``histogram_quantile``
    would produce, available in-process for the post-EOS tables.
    """

    KIND = "histogram"

    def __init__(self, name: str, labels: Dict[str, str],
                 buckets: Iterable[float] = LATENCY_BUCKETS_S):
        super().__init__(name, labels)
        self.bounds: Tuple[float, ...] = tuple(sorted(float(b)
                                                      for b in buckets))
        if not self.bounds:
            raise ValueError(f"{name}: histogram needs >= 1 bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +Inf tail slot
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, +Inf last."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for bound, n in zip(self.bounds, counts):
            acc += n
            out.append((bound, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-th percentile (0-100); None when empty."""
        cum = self.bucket_counts()
        total = cum[-1][1]
        if total == 0:
            return None
        rank = (q / 100.0) * total
        prev_bound, prev_cum = 0.0, 0
        for bound, c in cum:
            if c >= rank:
                if bound == float("inf"):
                    return prev_bound  # open-ended tail: lower bound
                if c == prev_cum:
                    return bound
                frac = (rank - prev_cum) / (c - prev_cum)
                return prev_bound + frac * (bound - prev_bound)
            prev_bound, prev_cum = bound, c
        return self.bounds[-1]


class MetricsRegistry:
    """Process-wide metric store + collector hooks + exporters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._collectors: List[Callable[[], Any]] = []

    # -- get-or-create ------------------------------------------------------
    def _get_or_create(self, cls, name: str, help_: str, labels: dict,
                       **kw) -> _Metric:
        key = (name, _label_key(labels))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if existing.KIND != cls.KIND:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.KIND}, not {cls.KIND}")
                return existing
            kind = self._kinds.get(name)
            if kind is not None and kind != cls.KIND:
                raise ValueError(
                    f"metric name {name!r} already used for kind {kind}")
            m = cls(name, labels, **kw)
            self._metrics[key] = m
            self._kinds[name] = cls.KIND
            if help_:
                self._help.setdefault(name, help_)
            return m

    def counter(self, name: str, help_: str = "", **labels) -> Counter:
        return self._get_or_create(Counter, name, help_, labels)

    def gauge(self, name: str, help_: str = "",
              fn: Optional[Callable[[], float]] = None, **labels) -> Gauge:
        g = self._get_or_create(Gauge, name, help_, labels, fn=fn)
        if fn is not None:
            g.fn = fn  # re-binding a callback gauge refreshes the source
        return g

    def histogram(self, name: str, help_: str = "",
                  buckets: Iterable[float] = LATENCY_BUCKETS_S,
                  **labels) -> Histogram:
        return self._get_or_create(Histogram, name, help_, labels,
                                   buckets=buckets)

    def get(self, name: str, **labels) -> Optional[_Metric]:
        """Look up an existing metric; None when absent."""
        with self._lock:
            return self._metrics.get((name, _label_key(labels)))

    # -- collectors ---------------------------------------------------------
    def register_collector(self, fn: Callable[[], Any]) -> None:
        """Register a scrape-time callback. Returning ``False`` (exactly)
        unregisters it — collectors holding weakrefs use this to clean
        up after their subject dies."""
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], Any]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        dead = []
        for fn in collectors:
            try:
                if fn() is False:
                    dead.append(fn)
            except Exception:  # noqa: BLE001 — one broken collector must
                # not take down the scrape endpoint
                dead.append(fn)
        if dead:
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]

    def collect(self) -> List[_Metric]:
        """Run collectors, then return all metrics (stable order)."""
        self._run_collectors()
        with self._lock:
            return [self._metrics[k] for k in sorted(
                self._metrics, key=lambda k: (k[0], k[1]))]

    # -- exporters ----------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        seen_header = set()
        for m in self.collect():
            if m.name not in seen_header:
                seen_header.add(m.name)
                help_ = self._help.get(m.name)
                if help_:
                    lines.append(f"# HELP {m.name} {help_}")
                lines.append(f"# TYPE {m.name} {m.KIND}")
            if isinstance(m, Histogram):
                for bound, c in m.bucket_counts():
                    le = "+Inf" if bound == float("inf") else repr(bound)
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(m.labels, {'le': le})} {c}")
                lines.append(
                    f"{m.name}_sum{_fmt_labels(m.labels)} {m.sum}")
                lines.append(
                    f"{m.name}_count{_fmt_labels(m.labels)} {m.count}")
            else:
                v = m.value
                out = repr(v) if isinstance(v, float) and not v.is_integer()\
                    else str(int(v))
                lines.append(f"{m.name}{_fmt_labels(m.labels)} {out}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able snapshot of every metric."""
        metrics: List[dict] = []
        for m in self.collect():
            entry: Dict[str, Any] = {
                "name": m.name, "type": m.KIND, "labels": m.labels,
            }
            if isinstance(m, Histogram):
                entry["count"] = m.count
                entry["sum"] = m.sum
                entry["buckets"] = [
                    ["+Inf" if b == float("inf") else b, c]
                    for b, c in m.bucket_counts()]
                entry["p50"] = m.percentile(50)
                entry["p99"] = m.percentile(99)
            else:
                entry["value"] = m.value
            metrics.append(entry)
        wall_ts = time.time()  # export timestamp: epoch seconds on the wire
        return {"ts": wall_ts, "metrics": metrics}

    def reset(self) -> None:
        """Drop every metric and collector (test isolation only: live
        instrumented objects keep references to detached metrics until
        they re-create them)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._help.clear()
            self._collectors.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _REGISTRY
