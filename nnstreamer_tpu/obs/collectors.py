"""Scrape-time collectors bridging live objects into the registry.

The per-element gauges are SAMPLED from each element's ``InvokeStats``
(the object behind the ``latency``/``throughput`` properties) rather
than double-counted on the hot path — the exported numbers therefore
agree with the in-band properties by construction, the consistency rule
the reference keeps between its property read-outs and its internal
framework statistics (tensor_filter.c:325-423).
"""

from __future__ import annotations

import weakref

from nnstreamer_tpu.obs.registry import MetricsRegistry, get_registry


def register_pipeline_collector(pipeline, registry: MetricsRegistry = None
                                ) -> None:
    """Export per-element latency/throughput/invoke gauges for every
    element of ``pipeline``, refreshed at each scrape. Holds only a
    weakref — a garbage-collected pipeline unregisters itself."""
    reg = registry or get_registry()
    ref = weakref.ref(pipeline)

    def collect():
        pipe = ref()
        if pipe is None:
            return False  # subject gone: drop this collector
        for el in pipe.elements:
            labels = {"pipeline": pipe.name, "element": el.name,
                      "type": el.ELEMENT_NAME}
            stats = el._metrics_stats()
            reg.gauge("nns_element_latency_us",
                      "Windowed avg invoke latency (µs), the element "
                      "latency property", **labels).set(stats.latency_us)
            reg.gauge("nns_element_throughput_milli",
                      "Outputs/sec x1000, the element throughput "
                      "property", **labels).set(stats.throughput_milli)
            reg.counter("nns_element_invokes_total",
                        "Cumulative chain invocations",
                        **labels).set_total(stats.total_invokes)
        return True

    reg.register_collector(collect)


def register_engine_collector(engine, registry: MetricsRegistry = None
                              ) -> None:
    """Export the serving engine's cumulative stats + occupancy gauges
    (weakref-bound like the pipeline collector)."""
    reg = registry or get_registry()
    ref = weakref.ref(engine)

    def collect():
        eng = ref()
        if eng is None:
            return False
        labels = {"engine": eng.obs_name}
        reg.gauge("nns_serving_active_streams",
                  "Streams currently holding a batch slot",
                  **labels).set(eng.active_streams)
        reg.gauge("nns_serving_batch_slots", "Configured batch slots (B)",
                  **labels).set(eng.B)
        slot_steps = eng.stats["slot_steps"]
        occupancy = (eng.stats["active_slot_steps"] / slot_steps
                     if slot_steps else 0.0)
        reg.gauge("nns_serving_batch_occupancy_ratio",
                  "Fraction of dispatched slot-steps that served a live "
                  "stream", **labels).set(occupancy)
        for key in ("tokens_generated", "dispatches", "prefills",
                    "prefill_chunks", "prefix_hits",
                    "prefix_tokens_reused"):
            reg.counter(f"nns_serving_{key}_total", **labels).set_total(
                eng.stats[key])
        return True

    reg.register_collector(collect)
