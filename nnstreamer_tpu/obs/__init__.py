"""obs — process-wide metrics registry + live telemetry export.

The reference measures itself per-filter at runtime (``latency`` /
``throughput`` properties, tensor_filter.c:325-423) and defers
pipeline-level visibility to external GstShark tracers. This package is
the pipeline-wide half, in-tree: every hot path (queue depth/drops,
rate drops, mux/merge sync wait, filter invokes, serving dispatches,
query/gRPC traffic) reports into ONE thread-safe registry with a stable
naming scheme::

    nns_<element>_<metric>{pipeline="...", element="..."}

and the registry exports three ways:

- :class:`MetricsServer` — HTTP endpoint serving Prometheus text
  exposition (``/metrics``) and a JSON snapshot (``/metrics.json``);
- ``Pipeline.metrics_snapshot()`` — in-process structured read;
- ``nns-launch --metrics-port`` — CLI wiring plus a post-EOS
  per-element table with drops and e2e p50/p99.

Per-element numbers are sampled from the SAME :class:`InvokeStats`
windows that back the element ``latency``/``throughput`` properties, so
the exported gauges always agree with the in-band read-outs.
"""

# FIRST import, before any sibling that creates module-level locks
# (registry's process registry, the flight recorder): when
# NNSTPU_LOCKGRAPH is set the lock factories must already be patched
# by the time those locks are created, or the witness misses them
from nnstreamer_tpu.obs import lockgraph  # noqa: F401
lockgraph.maybe_activate_env()

from nnstreamer_tpu.obs.registry import (  # noqa: E402,F401
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    get_registry,
)
from nnstreamer_tpu.obs.collectors import (  # noqa: F401
    register_engine_collector,
    register_pipeline_collector,
)
from nnstreamer_tpu.obs.server import MetricsServer  # noqa: F401
from nnstreamer_tpu.obs.timeline import (  # noqa: F401
    TRACE_SEQ_META,
    Timeline,
    jax_correlation,
    trace_enabled,
    tracing,
)
from nnstreamer_tpu.obs.quantiles import (  # noqa: F401
    BurnRateWindow,
    P2Quantile,
)
from nnstreamer_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    flight_enabled,
)
