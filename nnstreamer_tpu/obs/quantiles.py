"""Streaming quantile estimation and SLO burn-rate windows.

The flight recorder (``obs/flight.py``) runs on every frame of every
pipeline, always — it cannot afford the Timeline's approach of keeping
raw samples and sorting at report time, and it cannot afford the
histogram's fixed buckets (a 50 µs stage and a 5 s stall must both
resolve). This module provides the two bounded-memory estimators it
needs:

- :class:`P2Quantile` — the P² (piecewise-parabolic) algorithm of Jain
  & Chlamtac (1985): one quantile tracked with FIVE stored markers,
  O(1) per observation, no sample storage. Accuracy is within a few
  percent of the exact order statistic on smooth distributions and
  degrades gracefully on multi-modal ones (the marker heights settle on
  the mode containing the target rank).
- :class:`BurnRateWindow` — a sliding-window SLO burn rate in the
  multi-window alerting sense: the fraction of completions that
  breached the budget inside the window, divided by the error budget
  (1 - target). A burn rate of 1.0 means the pipeline is consuming its
  error budget exactly at the sustainable rate; the flight recorder
  pairs a fast and a slow window and warns only when BOTH exceed the
  threshold (a fast-only spike is noise, a slow-only excess is an old
  incident).

Both are internally locked: the flight recorder feeds them from sink /
lane / queue threads concurrently and exports them from the metrics
scrape thread.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import List, Optional


class P2Quantile:
    """One streaming quantile via the P² algorithm — five markers, no
    sample storage, O(1) per observation.

    ``observe()`` feeds a value; ``quantile()`` reads the current
    estimate (exact while fewer than five observations have arrived,
    the middle marker afterwards). Thread-safe.
    """

    __slots__ = ("p", "_lock", "_count",
                 "_heights", "_pos", "_want", "_dwant")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile p must be in (0, 1), got {p}")
        self.p = float(p)
        self._lock = threading.Lock()
        self._count = 0
        #: first five observations (sorted), then the five marker heights
        self._heights: List[float] = []
        self._pos: List[float] = []
        self._want: List[float] = []
        self._dwant = (0.0, self.p / 2.0, self.p,
                       (1.0 + self.p) / 2.0, 1.0)

    # -- recording -----------------------------------------------------------
    def observe(self, x: float) -> None:
        x = float(x)
        with self._lock:
            self._observe_locked(x)

    def _observe_locked(self, x: float) -> None:
        n = self._count
        self._count = n + 1
        h = self._heights
        if n < 5:
            # warm-up: exact storage of the first five observations,
            # bounded by construction (this branch only runs while the
            # list holds fewer than five values)
            bisect.insort(h, x)  # nns-lint: disable=NNS114 -- bounded: P² stores exactly five marker heights
            if n == 4:
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._want = [1.0 + 4.0 * d for d in self._dwant]
            return
        # locate the cell k containing x, clamping the extremes
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            for i in range(1, 4):
                if x >= h[i]:
                    k = i
        pos, want = self._pos, self._want
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            want[i] += self._dwant[i]
        # adjust the three interior markers toward their desired ranks
        for i in (1, 2, 3):
            d = want[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or \
                    (d <= -1.0 and pos[i - 1] - pos[i] < -1.0):
                s = 1.0 if d > 0 else -1.0
                cand = self._parabolic(i, s)
                if not (h[i - 1] < cand < h[i + 1]):
                    cand = self._linear(i, s)
                h[i] = cand
                pos[i] += s

    def _parabolic(self, i: int, s: float) -> float:
        h, n = self._heights, self._pos
        return h[i] + s / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + s) * (h[i + 1] - h[i])
            / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - s) * (h[i] - h[i - 1])
            / (n[i] - n[i - 1]))

    def _linear(self, i: int, s: float) -> float:
        h, n = self._heights, self._pos
        j = i + int(s)
        return h[i] + s * (h[j] - h[i]) / (n[j] - n[i])

    # -- reading -------------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def quantile(self) -> Optional[float]:
        """Current estimate; ``None`` before the first observation."""
        with self._lock:
            n = self._count
            if n == 0:
                return None
            if n <= 5:
                # exact order statistic while the warm-up buffer is all
                # we have (heights are kept sorted during warm-up)
                idx = min(n - 1, int(round(self.p * (n - 1))))
                return self._heights[idx]
            return self._heights[2]

    # -- serving continuity --------------------------------------------------
    def snapshot(self) -> dict:
        """The complete serializable marker state — restoring it into a
        fresh instance of the same ``p`` resumes the estimate exactly
        where the previous process left it (warm-up included)."""
        with self._lock:
            return {
                "count": self._count,
                "heights": list(self._heights),
                "pos": list(self._pos),
                "want": list(self._want),
            }

    def restore(self, state: dict) -> None:
        with self._lock:
            self._count = int(state["count"])
            self._heights = [float(v) for v in state["heights"]]
            self._pos = [float(v) for v in state["pos"]]
            self._want = [float(v) for v in state["want"]]


def _snapshot_cdf_points(snap: dict) -> Optional[tuple]:
    """Reduce one P² snapshot to piecewise-linear CDF support points
    ``(count, heights, cum_probs)``; ``None`` for an empty snapshot.

    Warm-up snapshots (count ≤ 5) hold exact sorted samples, so each
    sample sits at its mid-rank. Converged snapshots hold five markers
    whose ``pos`` entries are the marker's cumulative sample rank, so
    marker *i* approximates the ``(pos[i] - 0.5) / count`` quantile.
    """
    n = int(snap.get("count", 0))
    if n <= 0:
        return None
    heights = [float(v) for v in snap.get("heights", [])]
    if not heights:
        return None
    pos = [float(v) for v in snap.get("pos", [])]
    if n <= 5 or len(heights) < 5 or len(pos) < 5:
        heights = sorted(heights)
        probs = [(i + 0.5) / len(heights) for i in range(len(heights))]
        return n, heights, probs
    pairs = sorted(zip(heights, pos))
    hs: List[float] = []
    ps: List[float] = []
    run = 0.0
    for h, q in pairs:
        prob = min(1.0, max(0.0, (q - 0.5) / n))
        run = max(run, prob)  # CDF must be nondecreasing in both axes
        hs.append(h)
        ps.append(run)
    return n, hs, ps


def _cdf_eval(heights: List[float], probs: List[float], x: float) -> float:
    if x < heights[0]:
        return 0.0
    if x >= heights[-1]:
        return 1.0
    for i in range(len(heights) - 1):
        h0, h1 = heights[i], heights[i + 1]
        if h0 <= x <= h1:
            if h1 <= h0:
                return probs[i + 1]
            t = (x - h0) / (h1 - h0)
            return probs[i] + t * (probs[i + 1] - probs[i])
    return probs[-1]


def merge_p2_snapshots(snapshots: List[dict], p: float) -> Optional[float]:
    """Merge serialized :meth:`P2Quantile.snapshot` states from N
    independent processes into one fleet-level quantile estimate.

    Each snapshot's five markers define a piecewise-linear CDF through
    the marker heights at their cumulative ranks; the merged estimate
    inverts the count-weighted mixture of those CDFs at ``p``. This is
    the "marker merge" the federation layer uses: replicas ship marker
    state (40 bytes of floats), never raw samples, and the aggregate
    stays within P²-class accuracy of the pooled-sample exact quantile.
    Returns ``None`` when every snapshot is empty.
    """
    if not 0.0 < p < 1.0:
        raise ValueError(f"quantile p must be in (0, 1), got {p}")
    parts = []
    total = 0
    for snap in snapshots:
        pts = _snapshot_cdf_points(snap)
        if pts is None:
            continue
        parts.append(pts)
        total += pts[0]
    if total == 0:
        return None

    def mixture(x: float) -> float:
        acc = 0.0
        for n, hs, ps in parts:
            acc += n * _cdf_eval(hs, ps, x)
        return acc / total

    lo = min(hs[0] for _, hs, _ in parts)
    hi = max(hs[-1] for _, hs, _ in parts)
    if hi <= lo:
        return lo
    # the mixture CDF is monotone: invert by bisection on the value axis
    for _ in range(64):
        mid = 0.5 * (lo + hi)
        if mixture(mid) < p:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


class BurnRateWindow:
    """Sliding-window SLO burn rate over completion events.

    ``add(t, breached)`` records one completion; ``rate(now)`` returns
    ``breach_fraction / error_budget`` over the trailing ``window_s``
    seconds — 1.0 means the error budget is being consumed exactly at
    the sustainable rate, >1 means faster. The event deque is doubly
    bounded: by time (eviction at read and write) and by ``cap``
    entries, so a runaway completion rate cannot grow it.
    """

    def __init__(self, window_s: float, error_budget: float = 0.01,
                 cap: int = 4096):
        self.window_s = float(window_s)
        self.error_budget = max(float(error_budget), 1e-9)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(cap))
        self._breaches = 0

    def _evict_locked(self, now: float) -> None:
        horizon = now - self.window_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            _, breached = ev.popleft()
            if breached:
                self._breaches -= 1

    def add(self, t: float, breached: bool) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                # cap eviction: keep the running breach count honest
                _, old = self._events[0]
                if old:
                    self._breaches -= 1
            self._events.append((float(t), bool(breached)))
            if breached:
                self._breaches += 1
            self._evict_locked(float(t))

    def rate(self, now: float) -> float:
        with self._lock:
            self._evict_locked(float(now))
            n = len(self._events)
            if n == 0:
                return 0.0
            return (self._breaches / n) / self.error_budget

    def sample_count(self, now: float) -> int:
        with self._lock:
            self._evict_locked(float(now))
            return len(self._events)
