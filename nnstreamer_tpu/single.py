"""SingleShot — pipeline-less single invoke (L7 API surface).

Reference: ``gst/nnstreamer/tensor_filter/tensor_filter_single.c`` (431 LoC)
— a GObject wrapping tensor_filter_common without pads/caps, backing the
Tizen ``ml_single`` C-API: open the framework, invoke on demand, close.

Usage::

    s = SingleShot(framework="jax", model="mobilenet")  # or framework="auto"
    out = s.invoke([img])          # list in → list out
    s.close()                      # or use as a context manager
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from nnstreamer_tpu.elements.filter import detect_framework
from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, get_subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo
from nnstreamer_tpu.utils.stats import InvokeStats


class SingleShot:
    def __init__(self, framework: str = "auto", model: Optional[str] = None,
                 custom: Optional[str] = None,
                 accelerator: Optional[str] = None,
                 input_info: Optional[TensorsInfo] = None,
                 output_info: Optional[TensorsInfo] = None,
                 is_updatable: bool = False):
        if framework == "auto":
            if model is None:
                raise ValueError("SingleShot: framework=auto needs a model")
            framework = detect_framework(model)
            if framework is None:
                raise ValueError(f"cannot detect framework for {model!r}")
        factory = get_subplugin(FILTER, framework)
        if factory is None:
            raise ValueError(f"no filter backend {framework!r}")
        self.fw: FilterFramework = factory()
        self.stats = InvokeStats()
        self.fw.open(FilterProperties(
            model=model, custom=custom, accelerator=accelerator,
            input_info=input_info, output_info=output_info,
            is_updatable=is_updatable,
        ))

    # -- model info ----------------------------------------------------------
    def get_input_info(self) -> Optional[TensorsInfo]:
        return self.fw.get_model_info()[0]

    def get_output_info(self) -> Optional[TensorsInfo]:
        return self.fw.get_model_info()[1]

    def set_input_info(self, info: TensorsInfo) -> TensorsInfo:
        return self.fw.set_input_info(info)

    # -- invoke --------------------------------------------------------------
    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        with self.stats.measure():
            return self.fw.invoke(list(inputs))

    def reload_model(self, model: Optional[str] = None) -> None:
        self.fw.handle_event("reload_model",
                             {"model": model} if model else {})

    def close(self) -> None:
        self.fw.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
