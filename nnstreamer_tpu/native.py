"""ctypes bindings for the native runtime core (``native/libnnstpu.so``).

Every entry point has a pure-Python fallback, so the framework works
without the compiled library; with it, the host-side hot paths (wire
framing, sparse codec, checksums, aligned buffers) run GIL-free C++
(see ``native/nnstpu.cc`` for the reference-parity map).

Build on demand: ``python -m nnstreamer_tpu.native`` (runs make).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger

log = get_logger("native")

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_LIB_DIR, "libnnstpu.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def build(quiet: bool = True) -> bool:
    """Compile the native library (make -C native). A file lock serializes
    concurrent builders (SPMD multi-process starts) so nobody dlopens a
    half-written .so."""
    import fcntl

    lock_path = os.path.join(_LIB_DIR, ".build.lock")
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.isfile(_LIB_PATH) and \
                    os.path.getmtime(_LIB_PATH) >= _newest_source_mtime():
                return True  # another process already built it
            subprocess.run(["make", "-C", _LIB_DIR],
                           capture_output=quiet, check=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError) as e:
        log.warning("native build failed: %s", e)
        return False


def _newest_source_mtime() -> float:
    """Newest mtime across every native source — a .so older than ANY
    source (e.g. built before nnstpu_server.cc existed) must rebuild."""
    newest = 0.0
    try:
        for fn in os.listdir(_LIB_DIR):
            if fn.endswith((".cc", ".h")):
                newest = max(newest,
                             os.path.getmtime(os.path.join(_LIB_DIR, fn)))
    except OSError:
        pass
    return newest


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if sources are present but the .so is not)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.join(_LIB_DIR, "nnstpu.cc")
    stale = (os.path.isfile(_LIB_PATH) and os.path.isfile(src)
             and os.path.getmtime(_LIB_PATH) < _newest_source_mtime())
    if not os.path.isfile(_LIB_PATH) or stale:
        if os.path.isfile(src):
            if not build():
                return None
        else:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        log.warning("cannot load %s: %s", _LIB_PATH, e)
        return None
    lib.nnstpu_abi_version.restype = ctypes.c_int
    if lib.nnstpu_abi_version() != 1:
        log.warning("native ABI mismatch; rebuilding may help")
        return None
    try:
        return _bind(lib)
    except AttributeError as e:
        # a stale .so missing newer symbols (e.g. prebuilt before the
        # server core landed, with sources absent so no rebuild happened):
        # degrade to pure Python rather than crash at import
        log.warning("native library is missing symbols (%s); "
                    "rebuild with `python -m nnstreamer_tpu.native`", e)
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    global _lib
    # signatures
    lib.nnstpu_cpu_features.restype = ctypes.c_int
    lib.nnstpu_fnv1a.restype = ctypes.c_uint64
    lib.nnstpu_fnv1a.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.nnstpu_sparse_count.restype = ctypes.c_int64
    lib.nnstpu_sparse_count.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t]
    lib.nnstpu_sparse_encode.restype = ctypes.c_int64
    lib.nnstpu_sparse_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.nnstpu_sparse_decode.restype = ctypes.c_int
    lib.nnstpu_sparse_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
    lib.nnstpu_send_frame.restype = ctypes.c_int
    lib.nnstpu_send_frame.argtypes = [
        ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint64]
    lib.nnstpu_recv_header.restype = ctypes.c_int
    lib.nnstpu_recv_header.argtypes = [ctypes.c_int, ctypes.c_void_p]
    lib.nnstpu_recv_payload.restype = ctypes.c_int
    lib.nnstpu_recv_payload.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64]
    lib.nnstpu_set_nodelay.restype = ctypes.c_int
    lib.nnstpu_set_nodelay.argtypes = [ctypes.c_int]
    lib.nnstpu_server_start.restype = ctypes.c_void_p
    lib.nnstpu_server_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.nnstpu_server_port.restype = ctypes.c_int
    lib.nnstpu_server_port.argtypes = [ctypes.c_void_p]
    lib.nnstpu_server_take.restype = ctypes.c_int
    lib.nnstpu_server_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64)]
    lib.nnstpu_server_send.restype = ctypes.c_int
    lib.nnstpu_server_send.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint64]
    lib.nnstpu_server_kick.restype = ctypes.c_int
    lib.nnstpu_server_kick.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    # reference-wire extensions (absent in older .so builds — probed)
    if hasattr(lib, "nnstpu_server_start2"):
        lib.nnstpu_server_start2.restype = ctypes.c_void_p
        lib.nnstpu_server_start2.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int]
        lib.nnstpu_server_send_raw.restype = ctypes.c_int
        lib.nnstpu_server_send_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_uint64]
    lib.nnstpu_server_signal_stop.restype = None
    lib.nnstpu_server_signal_stop.argtypes = [ctypes.c_void_p]
    lib.nnstpu_server_stop.restype = None
    lib.nnstpu_server_stop.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# high-level helpers (native when possible, numpy fallback otherwise)
# ---------------------------------------------------------------------------
def cpu_features() -> dict:
    lib = get_lib()
    feats = lib.nnstpu_cpu_features() if lib else 0
    return {"neon": bool(feats & 1), "avx2": bool(feats & 2),
            "avx512": bool(feats & 4), "native": lib is not None}


def fnv1a(data: bytes) -> int:
    lib = get_lib()
    if lib:
        return int(lib.nnstpu_fnv1a(data, len(data)))
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def sparse_encode_arrays(dense: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """(indices u32, values) of nonzero elements, native-accelerated."""
    flat = np.ascontiguousarray(dense).reshape(-1)
    lib = get_lib()
    if lib is None or flat.dtype.itemsize not in (1, 2, 4, 8):
        idx = np.flatnonzero(flat).astype(np.uint32)
        return idx, flat[idx]
    nnz = lib.nnstpu_sparse_count(
        flat.ctypes.data, flat.size, flat.dtype.itemsize)
    if nnz < 0:
        idx = np.flatnonzero(flat).astype(np.uint32)
        return idx, flat[idx]
    idx = np.empty(nnz, np.uint32)
    vals = np.empty(nnz, flat.dtype)
    lib.nnstpu_sparse_encode(flat.ctypes.data, flat.size,
                             flat.dtype.itemsize,
                             idx.ctypes.data, vals.ctypes.data)
    return idx, vals


def sparse_decode_arrays(indices: np.ndarray, values: np.ndarray,
                         n_elems: int) -> np.ndarray:
    lib = get_lib()
    values = np.ascontiguousarray(values)
    indices = np.ascontiguousarray(indices, np.uint32)
    if lib is None:
        if len(indices) and int(indices.max()) >= n_elems:
            raise ValueError("sparse_decode: index out of range")
        dense = np.zeros(n_elems, values.dtype)
        dense[indices] = values
        return dense
    dense = np.empty(n_elems, values.dtype)
    rc = lib.nnstpu_sparse_decode(
        indices.ctypes.data, values.ctypes.data, len(indices),
        values.dtype.itemsize, dense.ctypes.data, n_elems)
    if rc != 0:
        raise ValueError("sparse_decode: index out of range")
    return dense


def send_frame(sock, magic: int, command: int, payload: bytes) -> None:
    """Framed send over a Python socket; native writev when available.

    The native path requires a truly blocking fd: CPython implements socket
    timeouts with O_NONBLOCK, and the C side retries only EINTR — so any
    socket with a timeout takes the Python path (same guard as recv_msg).
    """
    lib = get_lib()
    if lib is not None and sock.gettimeout() is None:
        rc = lib.nnstpu_send_frame(sock.fileno(), magic, command,
                                   payload, len(payload))
        if rc != 0:
            raise OSError("native send_frame failed")
        return
    import struct

    sock.sendall(struct.pack("<IIQ", magic, command, len(payload)) + payload)


class NativeServerCore:
    """Handle to the C++ epoll query-server transport (nnstpu_server.cc).

    Owns the listener + all client sockets on one native thread; Python
    sees only complete TRANSFER payloads (``wait_pop``) and pushes framed
    replies (``send``). Raises OSError if the native library is missing or
    the port cannot be bound — callers fall back to the pure-Python server.

    ``stop`` is safe against concurrent callers: it signals the native core
    (blocked takes return immediately), waits for in-flight calls to drain,
    and only then frees the handle.
    """

    #: initial take buffer; grows to the reported frame size on demand
    _INITIAL_CAP = 1 << 16

    def __init__(self, host: str, port: int, caps_str: str = "",
                 max_queue: int = 64, wire: int = 0):
        import threading

        lib = get_lib()
        if lib is None:
            raise OSError("native library unavailable")
        if wire and not hasattr(lib, "nnstpu_server_start2"):
            raise OSError("native library predates wire modes; rebuild")
        self._lib = lib
        self._cv = threading.Condition()
        self._inflight = 0
        #: per-thread reusable take buffer — idle polls (10/s in the
        #: serversrc loop) must not churn 64 KiB allocations
        self._tls = threading.local()
        if wire:
            # 1 = reference src port, 2 = reference sink port
            # (tensor_query_common.c framing — see nnstpu_server.cc)
            self._h = lib.nnstpu_server_start2(
                (host or "").encode(), int(port), caps_str.encode(),
                int(max_queue), int(wire))
        else:
            self._h = lib.nnstpu_server_start(
                (host or "").encode(), int(port), caps_str.encode(),
                int(max_queue))
        if not self._h:
            raise OSError(f"nnstpu_server: cannot bind {host}:{port}")
        self.port = int(lib.nnstpu_server_port(self._h))

    def _enter(self) -> Optional[int]:
        """Return the handle to use for ONE native call (never re-read
        self._h after this — a concurrent stop() nulls it, and the capture
        is what keeps the handle alive until _exit)."""
        with self._cv:
            if self._h is None:
                return None
            self._inflight += 1
            return self._h

    def _exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    def wait_pop(self, timeout: Optional[float]
                 ) -> Optional[Tuple[int, bytes]]:
        """Block up to ``timeout`` s (None = forever) for one TRANSFER;
        (client_id, payload) or None on timeout/stop. GIL released while
        waiting."""
        h = self._enter()
        if h is None:
            return None
        try:
            cid = ctypes.c_uint32()
            ln = ctypes.c_uint64()
            buf = getattr(self._tls, "buf", None)
            if buf is None:
                buf = self._tls.buf = bytearray(self._INITIAL_CAP)
            while True:
                # None = block forever: re-arm hour-long native waits (the
                # C side wants a finite ms value)
                step_ms = 3_600_000 if timeout is None \
                    else max(0, int(timeout * 1000))
                rc = self._lib.nnstpu_server_take(
                    h, step_ms,
                    (ctypes.c_char * len(buf)).from_buffer(buf), len(buf),
                    ctypes.byref(cid), ctypes.byref(ln))
                if rc == 0:
                    return int(cid.value), bytes(buf[:ln.value])
                if rc == -3:  # head frame bigger than our buffer: grow
                    buf = self._tls.buf = bytearray(ln.value)
                    continue
                if rc == -1 and timeout is None:
                    continue  # infinite wait: keep re-arming
                return None  # timeout or stopping
        finally:
            self._exit()

    def send(self, client_id: int, cmd: int, payload: bytes) -> bool:
        h = self._enter()
        if h is None:
            return False
        try:
            rc = self._lib.nnstpu_server_send(
                h, int(client_id), int(cmd), payload, len(payload))
            return rc == 0
        finally:
            self._exit()

    def send_raw(self, client_id: int, payload: bytes) -> bool:
        """Send pre-framed bytes (reference-wire results) to a client."""
        h = self._enter()
        if h is None:
            return False
        try:
            rc = self._lib.nnstpu_server_send_raw(
                h, int(client_id), payload, len(payload))
            return rc == 0
        finally:
            self._exit()

    def kick(self, client_id: int) -> None:
        """Disconnect one client (native parity with the pure-Python
        loop's close-on-bad-frame)."""
        h = self._enter()
        if h is None:
            return
        try:
            self._lib.nnstpu_server_kick(h, int(client_id))
        finally:
            self._exit()

    def stop(self) -> None:
        with self._cv:
            h, self._h = self._h, None
            if h is None:
                return
            self._lib.nnstpu_server_signal_stop(h)
            while self._inflight:
                self._cv.wait()
        self._lib.nnstpu_server_stop(h)


def main(argv=None):
    ok = build(quiet=False)
    print("native build:", "ok" if ok else "FAILED")
    print("features:", cpu_features())
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
