"""ctypes bindings for the native runtime core (``native/libnnstpu.so``).

Every entry point has a pure-Python fallback, so the framework works
without the compiled library; with it, the host-side hot paths (wire
framing, sparse codec, checksums, aligned buffers) run GIL-free C++
(see ``native/nnstpu.cc`` for the reference-parity map).

Build on demand: ``python -m nnstreamer_tpu.native`` (runs make).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger

log = get_logger("native")

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_LIB_DIR, "libnnstpu.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def build(quiet: bool = True) -> bool:
    """Compile the native library (make -C native). A file lock serializes
    concurrent builders (SPMD multi-process starts) so nobody dlopens a
    half-written .so."""
    import fcntl

    lock_path = os.path.join(_LIB_DIR, ".build.lock")
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.isfile(_LIB_PATH) and os.path.getmtime(
                    _LIB_PATH) >= os.path.getmtime(
                    os.path.join(_LIB_DIR, "nnstpu.cc")):
                return True  # another process already built it
            subprocess.run(["make", "-C", _LIB_DIR],
                           capture_output=quiet, check=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError) as e:
        log.warning("native build failed: %s", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if sources are present but the .so is not)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.join(_LIB_DIR, "nnstpu.cc")
    stale = (os.path.isfile(_LIB_PATH) and os.path.isfile(src)
             and os.path.getmtime(_LIB_PATH) < os.path.getmtime(src))
    if not os.path.isfile(_LIB_PATH) or stale:
        if os.path.isfile(src):
            if not build():
                return None
        else:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        log.warning("cannot load %s: %s", _LIB_PATH, e)
        return None
    lib.nnstpu_abi_version.restype = ctypes.c_int
    if lib.nnstpu_abi_version() != 1:
        log.warning("native ABI mismatch; rebuilding may help")
        return None
    # signatures
    lib.nnstpu_cpu_features.restype = ctypes.c_int
    lib.nnstpu_fnv1a.restype = ctypes.c_uint64
    lib.nnstpu_fnv1a.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.nnstpu_sparse_count.restype = ctypes.c_int64
    lib.nnstpu_sparse_count.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t]
    lib.nnstpu_sparse_encode.restype = ctypes.c_int64
    lib.nnstpu_sparse_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.nnstpu_sparse_decode.restype = ctypes.c_int
    lib.nnstpu_sparse_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
    lib.nnstpu_send_frame.restype = ctypes.c_int
    lib.nnstpu_send_frame.argtypes = [
        ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint64]
    lib.nnstpu_recv_header.restype = ctypes.c_int
    lib.nnstpu_recv_header.argtypes = [ctypes.c_int, ctypes.c_void_p]
    lib.nnstpu_recv_payload.restype = ctypes.c_int
    lib.nnstpu_recv_payload.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64]
    lib.nnstpu_set_nodelay.restype = ctypes.c_int
    lib.nnstpu_set_nodelay.argtypes = [ctypes.c_int]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# high-level helpers (native when possible, numpy fallback otherwise)
# ---------------------------------------------------------------------------
def cpu_features() -> dict:
    lib = get_lib()
    feats = lib.nnstpu_cpu_features() if lib else 0
    return {"neon": bool(feats & 1), "avx2": bool(feats & 2),
            "avx512": bool(feats & 4), "native": lib is not None}


def fnv1a(data: bytes) -> int:
    lib = get_lib()
    if lib:
        return int(lib.nnstpu_fnv1a(data, len(data)))
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def sparse_encode_arrays(dense: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """(indices u32, values) of nonzero elements, native-accelerated."""
    flat = np.ascontiguousarray(dense).reshape(-1)
    lib = get_lib()
    if lib is None or flat.dtype.itemsize not in (1, 2, 4, 8):
        idx = np.flatnonzero(flat).astype(np.uint32)
        return idx, flat[idx]
    nnz = lib.nnstpu_sparse_count(
        flat.ctypes.data, flat.size, flat.dtype.itemsize)
    if nnz < 0:
        idx = np.flatnonzero(flat).astype(np.uint32)
        return idx, flat[idx]
    idx = np.empty(nnz, np.uint32)
    vals = np.empty(nnz, flat.dtype)
    lib.nnstpu_sparse_encode(flat.ctypes.data, flat.size,
                             flat.dtype.itemsize,
                             idx.ctypes.data, vals.ctypes.data)
    return idx, vals


def sparse_decode_arrays(indices: np.ndarray, values: np.ndarray,
                         n_elems: int) -> np.ndarray:
    lib = get_lib()
    values = np.ascontiguousarray(values)
    indices = np.ascontiguousarray(indices, np.uint32)
    if lib is None:
        if len(indices) and int(indices.max()) >= n_elems:
            raise ValueError("sparse_decode: index out of range")
        dense = np.zeros(n_elems, values.dtype)
        dense[indices] = values
        return dense
    dense = np.empty(n_elems, values.dtype)
    rc = lib.nnstpu_sparse_decode(
        indices.ctypes.data, values.ctypes.data, len(indices),
        values.dtype.itemsize, dense.ctypes.data, n_elems)
    if rc != 0:
        raise ValueError("sparse_decode: index out of range")
    return dense


def send_frame(sock, magic: int, command: int, payload: bytes) -> None:
    """Framed send over a Python socket; native writev when available.

    The native path requires a truly blocking fd: CPython implements socket
    timeouts with O_NONBLOCK, and the C side retries only EINTR — so any
    socket with a timeout takes the Python path (same guard as recv_msg).
    """
    lib = get_lib()
    if lib is not None and sock.gettimeout() is None:
        rc = lib.nnstpu_send_frame(sock.fileno(), magic, command,
                                   payload, len(payload))
        if rc != 0:
            raise OSError("native send_frame failed")
        return
    import struct

    sock.sendall(struct.pack("<IIQ", magic, command, len(payload)) + payload)


def main(argv=None):
    ok = build(quiet=False)
    print("native build:", "ok" if ok else "FAILED")
    print("features:", cpu_features())
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
