"""nns-launch — the gst-launch-1.0 equivalent CLI.

The reference's CLI *is* ``gst-launch-1.0 <pipeline description>``
(Documentation/gst-launch-script-example.md). Same deal here::

    nns-launch "videotestsrc num-buffers=30 ! tensor_converter ! \
                tensor_filter framework=jax model=m.py ! tensor_sink"

Options:
  -q / --quiet     suppress the per-element stats summary
  -t / --timeout   seconds to wait for EOS (default: none — run to EOS)
  -v / --verbose   print caps as they are negotiated and buffer counts
  --confchk        print the effective configuration and registries
                   (the reference's tools/development/confchk) and exit
  --scaffold KIND NAME   generate subplugin boilerplate (the reference's
                   tools/development/nnstreamerCodeGenCustomFilter.py):
                   KIND ∈ {filter, decoder, converter}; writes
                   nnstreamer_tpu_<KIND>_<NAME>.py, the filename the
                   registry's external search discovers
"""

from __future__ import annotations

import argparse
import sys


def confchk() -> int:
    """Dump effective config + registries (reference confchk.c)."""
    import os

    from nnstreamer_tpu import native
    from nnstreamer_tpu import elements  # noqa: F401 — registers elements
    from nnstreamer_tpu.config import ENV_PREFIX, get_conf
    from nnstreamer_tpu.registry import (
        CONVERTER,
        DECODER,
        ELEMENT,
        FILTER,
        registered_names,
    )

    conf = get_conf(refresh=True)
    print("nnstreamer_tpu configuration")
    print(f"  conf file : {conf.path or '(none found)'}")
    envs = sorted(k for k in os.environ if k.startswith(ENV_PREFIX))
    print(f"  env overrides : {', '.join(envs) if envs else '(none)'}")
    allowed = conf.allowed_elements()
    print(f"  element restriction : "
          f"{'ENABLED' if allowed is not None else 'disabled'}")
    if allowed is not None:
        print(f"    allowlist: {', '.join(sorted(allowed)) or '(empty)'}")
    print(f"  native runtime : "
          f"{'available' if native.available() else 'NOT built'}")
    try:
        import jax

        # a TPU-tunnel sitecustomize may force the tunnel backend at boot;
        # honor an explicit JAX_PLATFORMS=cpu request (avoids a minutes-long
        # tunnel init just to print config)
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            jax.config.update("jax_platforms", "cpu")
        print(f"  jax backend : {jax.default_backend()} "
              f"({len(jax.devices())} device(s))")
    except Exception as e:  # noqa: BLE001
        print(f"  jax backend : unavailable ({e})")
    for kind, label in ((ELEMENT, "elements"), (FILTER, "filters"),
                        (DECODER, "decoders"), (CONVERTER, "converters")):
        names = registered_names(kind)
        print(f"  {label} ({len(names)}): {', '.join(names)}")
    return 0


_SCAFFOLDS = {
    "filter": '''"""Custom filter subplugin "{name}".

Drop this file's directory onto the filter search path and the registry
discovers it on first use (the reference's dlopen-from-conf-paths flow):

    export NNSTREAMER_TPU_FILTER_PATH=$PWD
    nns-launch "... ! tensor_filter framework={name} model=x ! ..."
"""

import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo


@subplugin(FILTER, "{name}")
class {cls}(FilterFramework):
    NAME = "{name}"

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        # load/prepare your model here; props.model / props.custom are set

    def get_model_info(self):
        # (None, None) = adapt to any input; set_input_info decides output.
        # Return fixed TensorsInfo pairs instead for a fixed-shape model.
        return None, None

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        self._info = in_info
        return in_info  # passthrough: output shapes = input shapes

    def invoke(self, inputs):
        # inputs: list of arrays; return list of output arrays
        return [np.asarray(x) for x in inputs]
''',
    "decoder": '''"""Custom decoder subplugin "{name}".

    export NNSTREAMER_TPU_DECODER_PATH=$PWD
    nns-launch "... ! tensor_decoder mode={name} ! ..."
"""

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin


@subplugin(DECODER, "{name}")
class {cls}:
    def out_caps(self, config, options) -> Caps:
        return Caps("other/tensors", {{"format": "flexible"}})

    def decode(self, buf, config, options):
        # buf.tensors are host numpy arrays; return a new TensorBuffer
        return buf.with_tensors([np.asarray(t) for t in buf.tensors])

    # Optional fused-device split — delete if host-only:
    # def device_kernel(self, options):
    #     def fn(consts, tensors):  # traced by JAX inside the fused region
    #         return tensors
    #     return None, fn
    # def host_finalize(self, host_buf, config, options):
    #     return host_buf
''',
    "converter": '''"""Custom converter subplugin "{name}".

    export NNSTREAMER_TPU_CONVERTER_PATH=$PWD
    nns-launch "... ! tensor_converter mode=custom-code:{name} ! ..."
"""

from nnstreamer_tpu.registry import CONVERTER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(CONVERTER, "{name}")
class {cls}:
    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        # parse buf.tensors (host arrays) into the tensors you want to emit
        return buf
''',
}


def scaffold(kind: str, name: str, out_dir: str = ".") -> int:
    """Write subplugin boilerplate (reference codegen tool equivalent)."""
    import keyword
    import os
    import re

    from nnstreamer_tpu.registry import external_subplugin_filename

    if kind not in _SCAFFOLDS:
        print(f"nns-launch: unknown scaffold kind {kind!r} "
              f"(choose from {', '.join(_SCAFFOLDS)})", file=sys.stderr)
        return 2
    if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_-]*", name):
        print(f"nns-launch: invalid subplugin name {name!r}", file=sys.stderr)
        return 2
    cls = "".join(p.capitalize() for p in re.split(r"[_-]+", name))
    # guard the generated class name: keywords ("none" → None), digit-leading
    # segments ("_1a" → 1a), or shadowing a template import ("caps" → Caps)
    if not cls or not cls[0].isalpha():
        cls = "Plugin" + cls
    if (not cls.isidentifier() or keyword.iskeyword(cls)
            or cls in ("TensorBuffer", "TensorsInfo", "Caps",
                       "FilterFramework", "FilterProperties")):
        cls += "Plugin"
    # the registry's external search looks for exactly this filename on the
    # NNSTREAMER_TPU_<KIND>_PATH search path
    path = os.path.join(out_dir, external_subplugin_filename(kind, name))
    if os.path.exists(path):
        print(f"nns-launch: {path} already exists", file=sys.stderr)
        return 2
    with open(path, "w") as f:
        f.write(_SCAFFOLDS[kind].format(name=name, cls=cls))
    print(f"wrote {path} ({kind} subplugin '{name}') — add its directory to "
          f"NNSTREAMER_TPU_{kind.upper()}_PATH to use it")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-launch",
        description="Run an nnstreamer_tpu pipeline description "
                    "(gst-launch-1.0 equivalent).",
    )
    ap.add_argument("description", nargs="*",
                    help="pipeline description (may be multiple tokens)")
    ap.add_argument("-t", "--timeout", type=float, default=None)
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--confchk", action="store_true",
                    help="print effective configuration and exit")
    ap.add_argument("--check", action="store_true",
                    help="statically verify the description and exit "
                         "without running it (same checks as nns-lint)")
    ap.add_argument("--scaffold", nargs=2, metavar=("KIND", "NAME"),
                    help="generate subplugin boilerplate "
                         "(filter|decoder|converter) and exit")
    ap.add_argument("--dot", metavar="FILE",
                    help="write the started pipeline graph (fused "
                         "regions included) as Graphviz dot to FILE")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live Prometheus metrics on "
                         "http://0.0.0.0:PORT/metrics (JSON at "
                         "/metrics.json) while the pipeline runs; "
                         "0 picks a free port (printed at startup)")
    ap.add_argument("--fleet", default=None, metavar="ENDPOINTS",
                    help="federate replica metrics: comma list of "
                         "host:port /metrics.json endpoints (or "
                         "op=NAME[,broker=HOST[:PORT]] for broker "
                         "discovery); merged fleet view served at "
                         "/fleet/metrics on the --metrics-port server")
    ap.add_argument("--export", nargs=2, metavar=("MODEL", "OUT"),
                    help="export a model (.py with get_model() / "
                         ".msgpack) as a compiled StableHLO artifact "
                         "and exit; see docs/model-artifacts.md")
    ap.add_argument("--platforms", default=None,
                    help="target platforms for --export (default tpu,cpu)")
    ap.add_argument("--custom", default=None,
                    help="custom options for --export (.msgpack factory)")
    ap.add_argument("--input", default=None,
                    help="input dims for --export (caps grammar, e.g. "
                         "3:224:224:1); overrides the model's declared "
                         "input info")
    ap.add_argument("--inputtype", default=None,
                    help="input types for --export (e.g. float32)")
    ap.add_argument("--inflight", type=int, default=None, metavar="K",
                    help="override the dispatch-window depth on every "
                         "element that has an 'inflight' property "
                         "(tensor_filter and fused regions); 0 forces "
                         "fully synchronous dispatch, the default is 2 "
                         "(see docs/profiling.md, Overlap tuning)")
    ap.add_argument("--lanes", type=int, default=None, metavar="N",
                    help="run the replicable pre-queue ingest segment "
                         "across N parallel worker lanes with in-order "
                         "reassembly (byte-identical output); 1 is the "
                         "serial path, NNSTPU_LANES overrides (see "
                         "docs/profiling.md, Ingest scaling)")
    ap.add_argument("--trace-out", metavar="FILE", default=None,
                    help="record a per-frame lifecycle timeline (lanes, "
                         "queue/EDF residency, dispatch fences, "
                         "transfers, decode, sink) and write it as "
                         "Perfetto/Chrome trace JSON to FILE at EOS; "
                         "prints the per-stage latency breakdown. "
                         "NNSTPU_TRACE=FILE does the same without the "
                         "flag (see docs/profiling.md, Frame timelines)")
    ap.add_argument("--flight-dir", metavar="DIR", default=None,
                    help="write rate-limited flight-recorder dumps "
                         "(full span detail around tail-latency "
                         "offenders, deadline breaches, faults, and "
                         "watchdog trips) as timestamped JSON files "
                         "under DIR; the always-on recorder itself "
                         "needs no flag — NNSTPU_FLIGHT=DIR does the "
                         "same, NNSTPU_FLIGHT=0 disables recording "
                         "entirely (see docs/profiling.md, Flight "
                         "recorder)")
    ap.add_argument("--checkpoint-dir", metavar="DIR", default=None,
                    help="arm serving continuity: restore durable "
                         "serving state (repo slots, scheduler "
                         "estimates, residency LRU order, latency "
                         "quantiles) from DIR at start when a "
                         "checkpoint exists, and write one at stop; "
                         "also arms the persistent XLA compile cache "
                         "under DIR/xla-cache so a second boot "
                         "performs zero serving-path compilations. "
                         "NNSTPU_CHECKPOINT=DIR does the same; unset "
                         "runs the byte-identical no-op path (see "
                         "docs/robustness.md, Serving continuity)")
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="arm only the persistent XLA compile cache at "
                         "DIR (no checkpoint/restore); "
                         "NNSTPU_COMPILE_CACHE=DIR does the same")
    ap.add_argument("--slo-budget-ms", type=float, default=None,
                    metavar="MS",
                    help="pipeline-wide SLO latency budget: activates "
                         "the serving scheduler (deadline admission "
                         "control, earliest-deadline-first ordering, "
                         "late-first load shedding, feedback-tuned "
                         "batch forming) on the admission-point queues; "
                         "unset/0 keeps the plain FIFO path (see "
                         "docs/profiling.md, SLO tuning)")
    ap.add_argument("--error-policy", default=None, metavar="POLICY",
                    choices=("halt", "skip-frame", "retry", "degrade"),
                    help="pipeline-default element error policy: halt "
                         "(fail fast, the default), skip-frame (drop "
                         "the failing frame and keep streaming), retry "
                         "(bounded exponential backoff), or degrade "
                         "(tensor_filter backend reload then CPU "
                         "fallback); per-element 'error-policy' "
                         "properties override (see docs/robustness.md)")
    ap.add_argument("--watchdog-s", type=float, default=None, metavar="S",
                    help="arm the pipeline watchdog: fail the pipeline "
                         "with a bus error when no frame progresses for "
                         "S seconds while work is in flight, instead of "
                         "hanging a stalled fence or EOS drain forever; "
                         "NNSTPU_WATCHDOG_S does the same without the "
                         "flag (see docs/robustness.md)")
    args = ap.parse_args(argv)

    if args.confchk:
        return confchk()
    if args.scaffold:
        return scaffold(*args.scaffold)
    if not args.export and (args.custom or args.input or args.inputtype
                            or args.platforms):
        ap.error("--platforms/--custom/--input/--inputtype only apply "
                 "with --export (in a pipeline description, set them as "
                 "element properties instead)")
    if args.export:
        from nnstreamer_tpu.filters.artifact import export_model

        model, out = args.export
        try:
            out_info = export_model(
                model, out, custom=args.custom,
                platforms=[p.strip() for p in
                           (args.platforms or "tpu,cpu").split(",")
                           if p.strip()],
                input_dims=args.input, input_types=args.inputtype)
        except Exception as e:  # noqa: BLE001 — CLI reports any failure
            print(f"nns-launch: export failed: {e}", file=sys.stderr)
            return 1
        print(f"Exported {model} -> {out} (outputs: {out_info})")
        return 0
    if not args.description:
        ap.error("pipeline description required (or --confchk)")

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.elements.sink import TensorSink

    desc = " ".join(args.description)
    if args.check:
        from nnstreamer_tpu.analysis.diagnostics import has_errors, \
            render_text
        from nnstreamer_tpu.analysis.verify import verify_description

        diags = verify_description(desc)
        if diags or not args.quiet:
            print(render_text(diags))
        return 1 if has_errors(diags) else 0
    try:
        pipe = parse_launch(desc)
    except (ValueError, KeyError) as e:
        print(f"nns-launch: parse error: {e}", file=sys.stderr)
        return 2

    if args.inflight is not None:
        for el in pipe.elements:
            if "inflight" in el._props:
                el.set_property("inflight", max(0, args.inflight))
    if args.lanes is not None:
        pipe.lanes = max(1, args.lanes)
    if args.slo_budget_ms is not None:
        pipe.slo_budget_ms = max(0.0, args.slo_budget_ms)
    if args.error_policy is not None:
        pipe.error_policy = args.error_policy
    if args.watchdog_s is not None:
        pipe.watchdog_s = max(0.0, args.watchdog_s)
    if args.flight_dir is not None:
        pipe.flight_dir = args.flight_dir
    if args.checkpoint_dir is not None:
        pipe.checkpoint_dir = args.checkpoint_dir
    if args.compile_cache is not None:
        from nnstreamer_tpu.pipeline.continuity import enable_compile_cache

        enable_compile_cache(args.compile_cache)

    if args.verbose:
        for el in pipe.elements:
            if isinstance(el, TensorSink):
                el.connect(lambda buf, name=el.name:
                           print(f"{name}: {buf!r}"))

    trace_tl = None
    if args.trace_out is not None:
        from nnstreamer_tpu.obs import timeline as _timeline

        trace_tl = _timeline.activate()
        trace_tl.export_path = args.trace_out

    metrics_srv = None
    if args.metrics_port is not None:
        from nnstreamer_tpu.obs import MetricsServer

        federation = None
        if args.fleet:
            federation = _parse_fleet(args.fleet)

        def _extra_sections(p=pipe):
            # slo/attribution/quantiles parity between the in-process
            # metrics_snapshot() and the scraped /metrics.json — what
            # fleet federation consumes from each replica
            snap = p.metrics_snapshot()
            return {k: snap[k] for k in ("slo", "attribution", "quantiles")
                    if k in snap}

        metrics_srv = MetricsServer(port=args.metrics_port,
                                    snapshot_fn=_extra_sections,
                                    federation=federation).start()
        print(f"Serving metrics on "
              f"http://0.0.0.0:{metrics_srv.port}/metrics")
        if federation is not None:
            print(f"Serving fleet federation on "
                  f"http://0.0.0.0:{metrics_srv.port}/fleet/metrics")

    print(f"Setting pipeline to PLAYING ({len(pipe.elements)} elements)...")
    try:
        try:
            if args.dot:
                # open BEFORE start so a bad path fails with nothing
                # running; fusion happens at start, so the dump shows the
                # real graph
                with open(args.dot, "w") as f:
                    pipe.start()
                    f.write(pipe.to_dot())
                print(f"Wrote pipeline graph to {args.dot}")
            msg = pipe.run(timeout=args.timeout)
        except Exception as e:  # noqa: BLE001 — CLI reports any failure
            pipe.stop()  # idempotent; reaps anything --dot start()ed
            print(f"nns-launch: ERROR: {e}", file=sys.stderr)
            return 1
        if msg is None:
            print("nns-launch: timeout waiting for EOS", file=sys.stderr)
            return 3
        print("Got EOS from pipeline.")

        if not args.quiet:
            _print_stats(pipe)
        if trace_tl is not None:
            try:
                trace_tl.export_chrome(args.trace_out)
            except OSError as e:
                print(f"nns-launch: trace export failed: {e}",
                      file=sys.stderr)
                return 1
            print(f"Wrote frame timeline to {args.trace_out} "
                  f"(load in ui.perfetto.dev)")
            _print_trace_breakdown(trace_tl)
        return 0
    finally:
        if trace_tl is not None:
            from nnstreamer_tpu.obs import timeline as _timeline

            _timeline.deactivate()
        # the exporter outlives EOS so a scraper can collect the final
        # counters; it stops only when the process is about to exit
        if metrics_srv is not None:
            metrics_srv.stop()


def _print_trace_breakdown(tl) -> None:
    """Post-EOS stage-breakdown footer for --trace-out: where a frame's
    end-to-end time went, and which stage owns the run's variance."""
    bd = tl.stage_breakdown()
    if not bd["frames"]:
        print("-- frame timeline: no completed frames recorded")
        return
    stages = " ".join(f"{k}={v:.2f}" for k, v in bd["stages_ms"].items()
                      if v > 0.0)
    print(f"-- frame timeline: {bd['frames']} frames, "
          f"e2e mean {bd['e2e_mean_ms']:.2f}ms, stages(ms) {stages}, "
          f"unattributed {bd['unattributed_ms']:.2f}ms "
          f"(reconciliation {bd['reconciliation']:.2f})")
    vr = tl.variance_report()
    if vr["dominant_stage"] is not None:
        print(f"-- frame timeline: e2e spread (MAD) "
              f"{vr['e2e_mad_ms']:.2f}ms, dominated by "
              f"{vr['dominant_stage']} "
              f"({vr['dominant_share']:.0%} of the spread)")


def _parse_fleet(spec: str):
    """``--fleet`` argument → FederatedMetrics: either a comma list of
    ``host:port`` scrape endpoints, or ``op=NAME[,broker=HOST[:PORT]]``
    for broker discovery of replicas advertising a metrics_port."""
    from nnstreamer_tpu.obs.distributed import FederatedMetrics

    parts = [p.strip() for p in spec.split(",") if p.strip()]
    if any(p.startswith("op=") for p in parts):
        operation = broker_host = None
        broker_port = 1883
        for p in parts:
            k, _, v = p.partition("=")
            if k == "op":
                operation = v
            elif k == "broker":
                h, _, pp = v.partition(":")
                broker_host = h
                if pp:
                    broker_port = int(pp)
        fed = FederatedMetrics(operation=operation,
                               broker_host=broker_host or "127.0.0.1",
                               broker_port=broker_port)
        fed.discover()
        return fed
    endpoints = []
    for p in parts:
        host, _, port = p.rpartition(":")
        endpoints.append((host or "127.0.0.1", int(port)))
    return FederatedMetrics(endpoints=endpoints)


def _print_stats(pipe) -> None:
    """Post-EOS per-element table from the metrics snapshot: the
    InvokeStats trio plus drops and end-to-end tail latency."""
    full = pipe.metrics_snapshot()
    snap = full["elements"]
    print("-- element stats (latency µs / throughput milli-out/s / "
          "invokes / drops / e2e p50,p99 ms)")
    for el in pipe.elements:
        s = snap[el.name]
        drops = s.get("drops", s.get("qos_drops"))
        e2e = (f"{s['e2e_p50_ms']:.1f},{s['e2e_p99_ms']:.1f}"
               if "e2e_p50_ms" in s else "-")
        print(f"  {el.name:28s} {s['latency_us']:>8d}  "
              f"{s['throughput_milli']:>10d}  {s['invokes']:>8d}  "
              f"{drops if drops is not None else '-':>6}  {e2e:>12s}")
    pool = full.get("pool")
    if pool and (pool["hits"] or pool["misses"]):
        print(f"-- ingest pool: hit-rate {pool['hit_rate']:.1%} "
              f"({pool['hits']} hits / {pool['misses']} misses, "
              f"{pool['outstanding']} outstanding)")
    for name, s in (full.get("lanes") or {}).items():
        print(f"-- ingest lanes {name}: {s['lanes']} lanes, "
              f"{s['forwarded']} frames, {s['ingest_fps']:.0f} fps, "
              f"reorder stall {s.get('reorder_stall_s', 0.0):.3f}s")
    sched = full.get("scheduler")
    if sched:
        print(f"-- slo scheduler: budget {sched['budget_ms']:.0f}ms, "
              f"{sched['admitted']} admitted / {sched['rejected']} "
              f"rejected / {sched['shed_late'] + sched['shed_capacity']} "
              f"shed, p99 {sched['p99_ms']:.1f}ms, "
              f"batch-cap {sched['batch_cap']}, "
              f"inflight {sched['inflight_target']}, "
              f"lanes-hint {sched['lanes_hint']}")
    mem = full.get("memory")
    if mem:
        mib = 1 << 20
        print(f"-- hbm budget: {mem['used_bytes'] / mib:.1f}/"
              f"{mem['budget_bytes'] / mib:.1f} MiB used "
              f"(high-water {mem['high_water_bytes'] / mib:.1f} MiB), "
              f"{mem['evictions']} evictions / "
              f"{mem['prefetches']} prefetches, "
              f"{mem['resident_units']} resident unit(s), "
              f"{mem['pressure_events']} pressure event(s)")
    slo = full.get("slo")
    if slo:
        e2e = slo["stages"].get("e2e")
        if e2e:
            print(f"-- flight recorder: {slo['completed']} frames, "
                  f"e2e p50 {e2e['p50_ms']:.2f}ms / "
                  f"p99 {e2e['p99_ms']:.2f}ms (streaming)")
        burn = slo.get("burn")
        if burn:
            print(f"-- slo burn: fast {burn['fast']:.2f}x / "
                  f"slow {burn['slow']:.2f}x of error budget "
                  f"(budget {burn['budget_ms']:.0f}ms"
                  f"{', OVERLOADED' if burn['overloaded'] else ''})")
        dumps = slo.get("dumps")
        if dumps and (dumps["written"] or dumps["suppressed"]):
            print(f"-- flight dumps: {dumps['written']} written / "
                  f"{dumps['suppressed']} rate-limited"
                  + (f", last: {dumps['paths'][-1]}"
                     if dumps["paths"] else ""))
    attr = full.get("attribution")
    if attr and attr.get("dominant_stage"):
        print(f"-- variance attribution: e2e spread (MAD) "
              f"{attr['e2e_mad_ms']:.2f}ms, dominated by "
              f"{attr['dominant_stage']} "
              f"({attr['dominant_share']:.0%} of the spread)"
              + (f", hints {attr['hints']}" if attr["hints"] else ""))


if __name__ == "__main__":
    sys.exit(main())
