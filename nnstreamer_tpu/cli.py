"""nns-launch — the gst-launch-1.0 equivalent CLI.

The reference's CLI *is* ``gst-launch-1.0 <pipeline description>``
(Documentation/gst-launch-script-example.md). Same deal here::

    nns-launch "videotestsrc num-buffers=30 ! tensor_converter ! \
                tensor_filter framework=jax model=m.py ! tensor_sink"

Options:
  -q / --quiet     suppress the per-element stats summary
  -t / --timeout   seconds to wait for EOS (default: none — run to EOS)
  -v / --verbose   print caps as they are negotiated and buffer counts
  --confchk        print the effective configuration and registries
                   (the reference's tools/development/confchk) and exit
"""

from __future__ import annotations

import argparse
import sys


def confchk() -> int:
    """Dump effective config + registries (reference confchk.c)."""
    import os

    from nnstreamer_tpu import native
    from nnstreamer_tpu import elements  # noqa: F401 — registers elements
    from nnstreamer_tpu.config import ENV_PREFIX, get_conf
    from nnstreamer_tpu.registry import (
        CONVERTER,
        DECODER,
        ELEMENT,
        FILTER,
        registered_names,
    )

    conf = get_conf(refresh=True)
    print("nnstreamer_tpu configuration")
    print(f"  conf file : {conf.path or '(none found)'}")
    envs = sorted(k for k in os.environ if k.startswith(ENV_PREFIX))
    print(f"  env overrides : {', '.join(envs) if envs else '(none)'}")
    restricted = conf.get_bool("element-restriction", "enable")
    print(f"  element restriction : "
          f"{'ENABLED' if restricted else 'disabled'}")
    if restricted:
        print(f"    allowlist: "
              f"{conf.get('element-restriction', 'restricted_elements')}")
    print(f"  native runtime : "
          f"{'available' if native.available() else 'NOT built'}")
    try:
        import jax

        # a TPU-tunnel sitecustomize may force the tunnel backend at boot;
        # honor an explicit JAX_PLATFORMS=cpu request (avoids a minutes-long
        # tunnel init just to print config)
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            jax.config.update("jax_platforms", "cpu")
        print(f"  jax backend : {jax.default_backend()} "
              f"({len(jax.devices())} device(s))")
    except Exception as e:  # noqa: BLE001
        print(f"  jax backend : unavailable ({e})")
    for kind, label in ((ELEMENT, "elements"), (FILTER, "filters"),
                        (DECODER, "decoders"), (CONVERTER, "converters")):
        names = registered_names(kind)
        print(f"  {label} ({len(names)}): {', '.join(names)}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-launch",
        description="Run an nnstreamer_tpu pipeline description "
                    "(gst-launch-1.0 equivalent).",
    )
    ap.add_argument("description", nargs="*",
                    help="pipeline description (may be multiple tokens)")
    ap.add_argument("-t", "--timeout", type=float, default=None)
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--confchk", action="store_true",
                    help="print effective configuration and exit")
    args = ap.parse_args(argv)

    if args.confchk:
        return confchk()
    if not args.description:
        ap.error("pipeline description required (or --confchk)")

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.elements.sink import TensorSink

    desc = " ".join(args.description)
    try:
        pipe = parse_launch(desc)
    except (ValueError, KeyError) as e:
        print(f"nns-launch: parse error: {e}", file=sys.stderr)
        return 2

    if args.verbose:
        for el in pipe.elements:
            if isinstance(el, TensorSink):
                el.connect(lambda buf, name=el.name:
                           print(f"{name}: {buf!r}"))

    print(f"Setting pipeline to PLAYING ({len(pipe.elements)} elements)...")
    try:
        msg = pipe.run(timeout=args.timeout)
    except Exception as e:  # noqa: BLE001 — CLI reports any failure
        print(f"nns-launch: ERROR: {e}", file=sys.stderr)
        return 1
    if msg is None:
        print("nns-launch: timeout waiting for EOS", file=sys.stderr)
        return 3
    print("Got EOS from pipeline.")

    if not args.quiet:
        print("-- element stats (latency µs / throughput milli-out/s / invokes)")
        for el in pipe.elements:
            s = el.stats.snapshot()
            print(f"  {el.name:28s} {s['latency_us']:>8d}  "
                  f"{s['throughput_milli']:>10d}  {s['total_invokes']:>8d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
