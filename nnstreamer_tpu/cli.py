"""nns-launch — the gst-launch-1.0 equivalent CLI.

The reference's CLI *is* ``gst-launch-1.0 <pipeline description>``
(Documentation/gst-launch-script-example.md). Same deal here::

    nns-launch "videotestsrc num-buffers=30 ! tensor_converter ! \
                tensor_filter framework=jax model=m.py ! tensor_sink"

Options:
  -q / --quiet     suppress the per-element stats summary
  -t / --timeout   seconds to wait for EOS (default: none — run to EOS)
  -v / --verbose   print caps as they are negotiated and buffer counts
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-launch",
        description="Run an nnstreamer_tpu pipeline description "
                    "(gst-launch-1.0 equivalent).",
    )
    ap.add_argument("description", nargs="+",
                    help="pipeline description (may be multiple tokens)")
    ap.add_argument("-t", "--timeout", type=float, default=None)
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.elements.sink import TensorSink

    desc = " ".join(args.description)
    try:
        pipe = parse_launch(desc)
    except (ValueError, KeyError) as e:
        print(f"nns-launch: parse error: {e}", file=sys.stderr)
        return 2

    if args.verbose:
        for el in pipe.elements:
            if isinstance(el, TensorSink):
                el.connect(lambda buf, name=el.name:
                           print(f"{name}: {buf!r}"))

    print(f"Setting pipeline to PLAYING ({len(pipe.elements)} elements)...")
    try:
        msg = pipe.run(timeout=args.timeout)
    except Exception as e:  # noqa: BLE001 — CLI reports any failure
        print(f"nns-launch: ERROR: {e}", file=sys.stderr)
        return 1
    if msg is None:
        print("nns-launch: timeout waiting for EOS", file=sys.stderr)
        return 3
    print("Got EOS from pipeline.")

    if not args.quiet:
        print("-- element stats (latency µs / throughput milli-out/s / invokes)")
        for el in pipe.elements:
            s = el.stats.snapshot()
            print(f"  {el.name:28s} {s['latency_us']:>8d}  "
                  f"{s['throughput_milli']:>10d}  {s['total_invokes']:>8d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
