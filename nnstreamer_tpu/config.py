"""Config system: ini file + environment-variable overrides.

Capability parity with the reference's three-layer config
(``nnstreamer_conf.c``, 717 LoC; ``nnstreamer.ini.in``):

1. an ini file — default ``/etc/nnstreamer_tpu.ini`` or
   ``$NNSTREAMER_TPU_CONF`` (reference envvar ``NNSTREAMER_CONF``,
   nnstreamer_conf.h:61);
2. env-var overrides ``NNSTREAMER_TPU_<GROUP>_<KEY>`` (reference
   ``NNSTREAMER_${group}_${key}``, nnstreamer_conf.h:149-164);
3. runtime element properties (handled by the elements themselves).

Recognized groups/keys mirror the reference's:
``[common] enable_envvar``, ``[filter] filters=<subplugin search paths>``,
``[filter] framework_priority_<ext>`` for framework auto-detection by model
extension (reference ``get_subplugin_priority``), and per-framework sections
(e.g. ``[jax] platform=tpu``).
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

ENV_CONF = "NNSTREAMER_TPU_CONF"
ENV_PREFIX = "NNSTREAMER_TPU_"
DEFAULT_CONF_PATHS = (
    os.path.expanduser("~/.config/nnstreamer_tpu.ini"),
    "/etc/nnstreamer_tpu.ini",
)

#: Compiled-model artifact extensions (filters/artifact.py loads these);
#: single source for both framework auto-detect and the jax backend's
#: artifact dispatch, so the two can never skew.
ARTIFACT_EXTS = (".jaxexp", ".stablehlo", ".mlir", ".mlirbc")

#: Default model-extension → framework priority (reference nnstreamer.ini.in
#: [filter] framework priorities). First loadable wins.
DEFAULT_EXT_PRIORITY: Dict[str, List[str]] = {
    ".msgpack": ["jax"],
    ".jax": ["jax"],
    ".orbax": ["jax"],
    **{ext: ["jax"] for ext in ARTIFACT_EXTS},
    ".pt": ["torch"],
    ".pth": ["torch"],
    ".pt2": ["torch"],
    ".tflite": ["tflite", "jax"],
    ".py": ["python"],
    ".so": ["native", "custom"],
}


class Conf:
    """Parsed configuration with env overrides. Thread-safe singleton via
    :func:`get_conf`."""

    def __init__(self, path: Optional[str] = None):
        self._cp = configparser.ConfigParser()
        self.path = path or os.environ.get(ENV_CONF)
        if not self.path:
            for p in DEFAULT_CONF_PATHS:
                if os.path.isfile(p):
                    self.path = p
                    break
        if self.path and os.path.isfile(self.path):
            self._cp.read(self.path)

    def get(self, group: str, key: str, default: Optional[str] = None):
        """Env override first (NNSTREAMER_TPU_<GROUP>_<KEY>), then ini.
        Hyphenated group names (e.g. ``element-restriction``) also match
        their underscore spelling — a shell cannot export a variable
        with ``-`` in its name."""
        for g in (group.upper(), group.upper().replace("-", "_")):
            env = os.environ.get(f"{ENV_PREFIX}{g}_{key.upper()}")
            if env is not None:
                return env
        return self._cp.get(group, key, fallback=default)

    def get_bool(self, group: str, key: str, default: bool = False) -> bool:
        v = self.get(group, key)
        if v is None:
            return default
        return str(v).strip().lower() in ("1", "true", "yes", "on")

    def subplugin_paths(self, kind: str) -> List[str]:
        """Extra search paths for dynamically-discovered subplugins
        (reference [filter]/[decoder]/[converter] path keys)."""
        raw = self.get(kind, "path", "") or ""
        return [p for p in raw.split(os.pathsep) if p]

    def allowed_elements(self) -> Optional[set]:
        """Element allowlist, or ``None`` when restriction is off
        (reference ``enable-element-restriction`` +
        ``allowed-elements``, meson_options.txt:39-40; the reference's
        value is space-separated — both space and comma work here).

        Section ``[element-restriction]``: ``enable_element_restriction``
        (or ``enable``) turns it on; ``allowed_elements`` (or the
        reference-era ``restricted_elements`` key) names the permitted
        factories. Restricted pipelines fail closed at parse time."""
        if not (self.get_bool("element-restriction",
                              "enable_element_restriction")
                or self.get_bool("element-restriction", "enable")):
            return None
        raw = (self.get("element-restriction", "allowed_elements")
               or self.get("element-restriction", "restricted_elements")
               or "")
        return {e for e in raw.replace(",", " ").split() if e}

    def framework_priority(self, model_path: str) -> List[str]:
        """Framework candidates for a model file, best first (reference
        framework auto-detect by extension, tensor_filter_common.c:1200)."""
        ext = os.path.splitext(model_path)[1].lower()
        key = f"framework_priority_{ext.lstrip('.')}"
        raw = self.get("filter", key)
        if raw:
            return [f.strip() for f in raw.split(",") if f.strip()]
        return list(DEFAULT_EXT_PRIORITY.get(ext, []))


_conf: Optional[Conf] = None
_lock = threading.Lock()


def get_conf(refresh: bool = False) -> Conf:
    global _conf
    with _lock:
        if _conf is None or refresh:
            _conf = Conf()
        return _conf
