"""TensorFlow filter backend — direct in-process SavedModel/GraphDef
ingestion.

Reference: ``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc``
(785 LoC) runs TF graphs in-process via libtensorflow Session::Run. The
TPU-native route never runs TF at stream time: at ``open()`` the graph
is staged once through TF's own XLA bridge —
``tf.function(jit_compile=True)`` →
``experimental_get_compiler_ir(stage="stablehlo")`` — and the resulting
StableHLO module is wrapped into a ``jax.export.Exported``
(``filters/artifact.py`` raw-module path). From then on the model is an
ordinary jittable XLA callee: device-resident, fusable into pipeline
regions, no TF in the hot loop.

``framework=tensorflow model=saved_model_dir`` (or ``model.pb`` frozen
GraphDef with ``inputname``/``outputname`` in the ``custom`` option,
mirroring the reference's required input/output properties). The
offline export recipe (docs/model-artifacts.md) remains the fallback
when ``tensorflow`` is not importable.
"""

from __future__ import annotations

import os
from typing import Optional

from nnstreamer_tpu.filters.jax_backend import JaxFilter
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.registry import FILTER, subplugin

log = get_logger("filter.tf")


def have_tensorflow() -> bool:
    try:
        import tensorflow  # noqa: F401

        return True
    except ImportError:
        return False


from nnstreamer_tpu.filters.api import parse_custom as _parse_custom


def _concrete_to_stablehlo(tf_callable, specs, name: str):
    """Stage a TF callable to StableHLO text via TF's XLA bridge and
    wrap it as a jax.export.Exported (platform-agnostic raw module)."""
    import jax
    import tensorflow as tf

    from nnstreamer_tpu.filters.artifact import _exported_from_raw_module

    fn = tf.function(tf_callable, jit_compile=True,
                     input_signature=specs)
    ir = fn.experimental_get_compiler_ir(*specs)(stage="stablehlo")
    if isinstance(ir, bytes):
        ir = ir.decode()
    return _exported_from_raw_module(ir.encode(), jax.default_backend(),
                                     name)


def _static_specs(specs, model: str):
    import tensorflow as tf

    fixed = []
    for s in specs:
        if s.shape.rank is None or any(d is None for d in s.shape):
            raise ValueError(
                f"tensorflow: {model!r} input {s.name or ''} has dynamic "
                f"shape {s.shape} — XLA needs static shapes; set the "
                "input property on tensor_filter (input=DIMS "
                "inputtype=TYPE) to pin it")
        fixed.append(tf.TensorSpec(s.shape, s.dtype, name=s.name))
    return fixed


def _stage_entry(call, specs, model: str, what: str) -> dict:
    """Stage a TF callable and build the backend entry dict
    (fn/params/in_info/out_info) — the same shape ``artifact_entry``
    returns."""
    from nnstreamer_tpu.filters.artifact import artifact_tensors_info

    exp = _concrete_to_stablehlo(call, specs, os.path.basename(model))
    in_info, out_info = artifact_tensors_info(exp)
    log.info("tensorflow: staged %s %s to StableHLO (%d inputs -> %d "
             "outputs)", what, model, len(in_info), len(out_info))

    def fn(*xs):
        out = exp.call(*xs)
        return out if isinstance(out, (list, tuple)) else (out,)

    return dict(fn=fn, params=None, in_info=in_info, out_info=out_info,
                exported=exp)


def saved_model_entry(model: str, signature: Optional[str] = None,
                      props_in_info=None) -> dict:
    """SavedModel dir → backend entry dict (fn/params/in_info/out_info),
    the same shape ``artifact_entry`` returns."""
    import tensorflow as tf

    sm = tf.saved_model.load(model)
    sig_name = signature or "serving_default"
    if sig_name not in sm.signatures:
        raise ValueError(
            f"tensorflow: SavedModel {model!r} has no signature "
            f"{sig_name!r} (available: {sorted(sm.signatures)})")
    cf = sm.signatures[sig_name]
    kwargs_sig = cf.structured_input_signature[1]
    names = sorted(kwargs_sig)  # deterministic positional order (matches
    # TF nest's sorted-key dict flattening, so frozen.inputs line up)
    specs = [kwargs_sig[n] for n in names]
    if props_in_info is not None and len(props_in_info) == len(specs):
        # user-pinned dims (innermost-first) override dynamic dims
        specs = [tf.TensorSpec(tuple(reversed(ti.dim)), s.dtype,
                               name=s.name)
                 for ti, s in zip(props_in_info, specs)]
    specs = _static_specs(specs, model)
    # freeze captured variables into graph constants — otherwise TF's
    # XLA bridge lifts every variable as an extra module parameter and
    # the staged StableHLO signature stops matching the tensor stream
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    frozen = convert_variables_to_constants_v2(cf)

    def call(*xs):
        out = frozen(*xs)
        return list(out) if isinstance(out, (list, tuple)) else [out]

    return _stage_entry(call, specs, model,
                        f"SavedModel sig={sig_name}")


def graphdef_entry(model: str, custom: Optional[str] = None,
                   props_in_info=None) -> dict:
    """Frozen GraphDef ``.pb`` → backend entry. Needs tensor names the
    way the reference does (tensor_filter_tensorflow.cc requires
    input/output properties): ``custom="inputname:x,outputname:y"``
    (comma-separate multiple names with ``;``)."""
    import tensorflow as tf

    opts = _parse_custom(custom)
    in_names = [n for n in opts.get("inputname", "").split(";") if n]
    out_names = [n for n in opts.get("outputname", "").split(";") if n]
    if not in_names or not out_names:
        raise ValueError(
            "tensorflow: a frozen GraphDef needs tensor names — pass "
            'custom="inputname:input0,outputname:logits" on tensor_filter '
            "(the reference requires the same via input/output props, "
            "tensor_filter_tensorflow.cc)")
    gd = tf.compat.v1.GraphDef()
    with open(model, "rb") as f:
        gd.ParseFromString(f.read())

    def _name(t):
        return t if ":" in t else t + ":0"

    wrapped = tf.compat.v1.wrap_function(
        lambda: tf.compat.v1.import_graph_def(gd, name=""), [])
    cf = wrapped.prune([_name(n) for n in in_names],
                       [_name(n) for n in out_names])
    # pruned wrap_functions carry no structured signature; their flat
    # .inputs are the placeholders in the order prune() was given
    specs = [tf.TensorSpec(t.shape, t.dtype) for t in cf.inputs]
    if props_in_info is not None and len(props_in_info) == len(specs):
        specs = [tf.TensorSpec(tuple(reversed(ti.dim)), s.dtype)
                 for ti, s in zip(props_in_info, specs)]
    specs = _static_specs(specs, model)

    def call(*xs):
        out = cf(*xs)
        return list(out) if isinstance(out, (list, tuple)) else [out]

    return _stage_entry(call, specs, model, "GraphDef")


def tf_model_entry(model: str, custom: Optional[str] = None,
                   props_in_info=None) -> dict:
    opts = _parse_custom(custom)
    if os.path.isdir(model):
        return saved_model_entry(model, signature=opts.get("signature"),
                                 props_in_info=props_in_info)
    return graphdef_entry(model, custom=custom, props_in_info=props_in_info)


@subplugin(FILTER, "tensorflow")
class TensorFlowFilter(JaxFilter):
    """framework=tensorflow — SavedModel/.pb staged through XLA at open().

    Execution inherits the jax backend wholesale (device placement, jit,
    fusion, stats): after staging, a TF model IS a jax model."""

    NAME = "tensorflow"

    def _load(self, model: str, props):
        if not have_tensorflow():
            raise RuntimeError(
                "tensorflow: the tensorflow package is not importable in "
                "this environment; export the model offline to StableHLO "
                "instead (docs/model-artifacts.md, 'TensorFlow models') "
                "and load it with framework=jax")
        is_pb = model.endswith(".pb") and os.path.isfile(model)
        is_sm = os.path.isdir(model) and (
            os.path.isfile(os.path.join(model, "saved_model.pb")) or
            os.path.isfile(os.path.join(model, "saved_model.pbtxt")))
        if not (is_pb or is_sm):
            raise ValueError(
                f"tensorflow: {model!r} is neither a SavedModel directory "
                "nor a frozen .pb GraphDef")
        return tf_model_entry(model, custom=props.custom,
                              props_in_info=props.input_info)
