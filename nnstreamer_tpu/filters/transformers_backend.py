"""HuggingFace ``transformers`` filter backend.

The reference wraps the era's heavyweight NN frameworks as filter
subplugins (``tensor_filter_tensorflow.cc`` 785 LoC,
``tensor_filter_pytorch.cc`` 711 LoC): model file in, tensors in/out. The
TPU-native peer is the transformers model hub format: the ``model``
property names a local HF checkpoint directory or a ``config.json``, and
the backend runs the **Flax** head of the architecture jitted on TPU
(falling back to torch-CPU only if the architecture has no Flax class or
``custom=backend:torch`` forces it).

Inputs map positionally: ``input_ids`` [, ``attention_mask``] — i.e. a
text pipeline is ``tensor_converter`` (text→int ids) ! ``tensor_filter
framework=transformers model=./bert-dir``; outputs are the model outputs
flattened in declaration order (logits first for classification heads).

``custom=`` options (comma-separated ``key:value``):

- ``arch:<FlaxAutoModelFor...|AutoModelFor...>`` — auto-class to load
  with (default ``FlaxAutoModel``).
- ``backend:flax|torch`` — force a backend (default flax).
- ``from_config:true`` — build from config with random weights (no
  weight files needed; CI/egress-free pattern, like the reference's
  EdgeTPU ``device_type:dummy`` software mock).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


from nnstreamer_tpu.filters.api import parse_custom as _parse_custom


@subplugin(FILTER, "transformers")
class TransformersFilter(FilterFramework):
    NAME = "transformers"
    KEEP_ON_DEVICE = True

    def __init__(self):
        super().__init__()
        self._model = None
        self._params = None
        self._backend = "flax"
        self._jitted = None

    # -- helpers -------------------------------------------------------------
    def _auto_cls(self, name: str):
        import transformers

        if not hasattr(transformers, name):
            raise ValueError(f"transformers: unknown auto-class {name!r}")
        return getattr(transformers, name)

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        import transformers

        opts = _parse_custom(props.custom)
        self._backend = opts.get("backend", "flax")
        arch = opts.get(
            "arch", "FlaxAutoModel" if self._backend == "flax" else "AutoModel"
        )
        if self._backend == "flax" and not arch.startswith("Flax"):
            arch = "Flax" + arch
        path = props.model
        if not path:
            raise ValueError("transformers: model property required")
        cfg = transformers.AutoConfig.from_pretrained(
            path, local_files_only=True
        )
        cls = self._auto_cls(arch)
        from_config = opts.get("from_config", "").lower() in ("1", "true")
        if self._backend == "flax":
            if from_config:
                self._model = cls.from_config(cfg)
            else:
                self._model = cls.from_pretrained(
                    path, config=cfg, local_files_only=True
                )
            self._params = self._model.params
            self._compile()
        else:
            import torch

            if from_config:
                self._model = cls.from_config(cfg)
            else:
                self._model = cls.from_pretrained(
                    path, config=cfg, local_files_only=True
                )
            self._model.eval()
            self._torch = torch

    def _compile(self):
        import jax

        model = self._model

        def fwd(params, input_ids, attention_mask):
            out = model(
                input_ids=input_ids,
                attention_mask=attention_mask,
                params=params,
                train=False,
            )
            return tuple(
                v for v in out.to_tuple()
                if hasattr(v, "shape") and v is not None
            )

        self._fwd = fwd
        self._jitted = jax.jit(fwd)

    def close(self) -> None:
        self._model = self._params = self._jitted = None
        super().close()

    # -- shape negotiation ---------------------------------------------------
    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        import jax

        ids = in_info[0]
        if self._backend == "torch":
            outs = self.invoke(
                [np.zeros(t.shape, t.type.np_dtype) for t in in_info]
            )
            return TensorsInfo.from_arrays(outs)
        dummy_ids = jax.ShapeDtypeStruct(ids.shape, np.int32)
        dummy_mask = jax.ShapeDtypeStruct(ids.shape, np.int32)
        outs = jax.eval_shape(
            self._fwd, self._params, dummy_ids, dummy_mask
        )
        return TensorsInfo([
            TensorInfo(dim=tuple(reversed(o.shape)),
                       type=TensorType.from_any(np.dtype(o.dtype)))
            for o in outs
        ])

    # -- invoke --------------------------------------------------------------
    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        with self.global_stats().measure():
            if self._backend == "torch":
                t = self._torch
                ids = t.as_tensor(np.asarray(inputs[0])).long()
                mask = (
                    t.as_tensor(np.asarray(inputs[1])).long()
                    if len(inputs) > 1 else t.ones_like(ids)
                )
                with t.no_grad():
                    out = self._model(input_ids=ids, attention_mask=mask)
                return [
                    v.numpy() for v in out.to_tuple()
                    if hasattr(v, "numpy")
                ]
            import jax.numpy as jnp

            ids = jnp.asarray(inputs[0], jnp.int32)
            mask = (
                jnp.asarray(inputs[1], jnp.int32)
                if len(inputs) > 1 else jnp.ones_like(ids)
            )
            return list(self._jitted(self._params, ids, mask))
