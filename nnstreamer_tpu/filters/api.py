"""The filter-framework subplugin API (the reference's single most important
extension point).

Reference: ``GstTensorFilterFramework`` v1 vtable —
``open/close/invoke/getModelInfo/eventHandler``
(gst/nnstreamer/include/nnstreamer_plugin_api_filter.h:273-495) — plus the
cross-instance shared-model representation
(``nnstreamer_filter_shared_model_get/insert/remove``, :577-602) and
per-framework cumulative statistics (:169-174).

Backends subclass :class:`FilterFramework` and register with
``register_subplugin(FILTER, name, cls)`` (the .so-constructor
``nnstreamer_filter_probe`` analog). The element never touches backend
internals; arrays cross the boundary as numpy or device ``jax.Array``s —
backends declare ``KEEP_ON_DEVICE`` to receive/return device arrays so a
chain of device-aware elements never bounces tensors to host.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from nnstreamer_tpu.tensors.types import TensorsInfo
from nnstreamer_tpu.utils.stats import InvokeStats


def parse_custom(custom: Optional[str]) -> Dict[str, str]:
    """Parse the backend-agnostic ``custom`` option string:
    comma-separated ``key:value`` (or ``key=value``) pairs. Values may
    carry ';'-separated lists (e.g. multiple tensor names) — the comma
    is the only pair separator."""
    out: Dict[str, str] = {}
    for part in (custom or "").split(","):
        part = part.strip()
        if not part:
            continue
        sep = ":" if ":" in part else "="
        k, _, v = part.partition(sep)
        out[k.strip()] = v.strip()
    return out


@dataclasses.dataclass
class FilterProperties:
    """Everything a backend needs at open() time (reference
    ``GstTensorFilterProperties``)."""

    model: Optional[str] = None          # path(s), comma-separated
    custom: Optional[str] = None         # backend-specific option string
    accelerator: Optional[str] = None    # e.g. "true:tpu", "true:cpu"
    mesh: Optional[str] = None           # serving mesh spec, e.g. "dp4",
    # "dp2xtp2" (parallel/serve.py grammar); None = single device
    input_info: Optional[TensorsInfo] = None   # user-forced input shapes
    output_info: Optional[TensorsInfo] = None  # user-forced output shapes
    is_updatable: bool = False           # model hot-reload allowed
    shared_key: Optional[str] = None     # shared-tensor-filter-key
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def models(self) -> List[str]:
        return [m.strip() for m in (self.model or "").split(",") if m.strip()]


class FilterFramework:
    """Backend base class (the v1 vtable).

    Lifecycle: ``open(props)`` → ``get_model_info()`` / ``set_input_info()``
    → ``invoke()``×N → ``close()``. ``handle_event`` receives custom events
    (e.g. ``reload_model``, reference RELOAD_MODEL,
    nnstreamer_plugin_api_filter.h:377-383).
    """

    #: registry name; subclasses override.
    NAME = "base"
    #: backend accepts/returns device jax.Arrays (no host bounce).
    KEEP_ON_DEVICE = False
    #: per-framework cumulative stats (reference
    #: GstTensorFilterFrameworkStatistics) — keyed by NAME.
    _GLOBAL_STATS: Dict[str, InvokeStats] = {}
    _GLOBAL_STATS_LOCK = threading.Lock()

    def __init__(self):
        self.props: Optional[FilterProperties] = None

    # -- vtable --------------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        self.props = props

    def close(self) -> None:
        self.props = None

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        raise NotImplementedError

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        """(input_info, output_info); either may be None if the backend can
        adapt to any input (then set_input_info decides)."""
        return None, None

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        """Given negotiated input shapes, return output shapes (reference
        getModelInfo(SET_INPUT_INFO))."""
        raise NotImplementedError(
            f"{self.NAME}: cannot infer output info from input"
        )

    def handle_event(self, name: str, data: Dict[str, Any]) -> None:
        """Custom events; ``reload_model`` by default re-opens."""
        if name == "reload_model" and self.props is not None:
            if not self.props.is_updatable:
                raise RuntimeError(
                    f"{self.NAME}: reload requested but is-updatable=false"
                )
            if "model" in data:
                self.props.model = data["model"]
            self.reload()

    def reload(self) -> None:
        props = self.props
        self.close()
        self.open(props)

    # -- framework-wide stats ------------------------------------------------
    @classmethod
    def global_stats(cls) -> InvokeStats:
        with cls._GLOBAL_STATS_LOCK:
            if cls.NAME not in cls._GLOBAL_STATS:
                cls._GLOBAL_STATS[cls.NAME] = InvokeStats(window=100)
            return cls._GLOBAL_STATS[cls.NAME]


# --------------------------------------------------------------------------
# Shared model representation (reference nnstreamer_plugin_api_filter.h:
# 577-602): instances with the same shared-tensor-filter-key reuse one
# loaded model (e.g. one set of device-resident params for N pipelines).
# --------------------------------------------------------------------------
_shared: Dict[str, Any] = {}
_shared_lock = threading.Lock()


def shared_model_get(key: str) -> Optional[Any]:
    with _shared_lock:
        return _shared.get(key)


def shared_model_insert(key: str, model: Any) -> Any:
    """Insert if absent; returns the representative instance."""
    with _shared_lock:
        return _shared.setdefault(key, model)


def shared_model_remove(key: str) -> bool:
    with _shared_lock:
        return _shared.pop(key, None) is not None
