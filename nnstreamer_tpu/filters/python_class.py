"""Python-class filter backend (reference ``tensor_filter_python3.cc``,
842 LoC + helper ``nnstreamer_python3_helper.cc``).

The reference embeds CPython and loads a user script defining a class with
``getInputDim/getOutputDim/setInputDim/invoke``; here the host language *is*
Python, so the backend imports the script and duck-types the same protocol
(both reference-style camelCase and snake_case method names are accepted)::

    # model file my_filter.py
    class Filter:
        def get_input_info(self): ...   # or getInputDim
        def get_output_info(self): ...  # or getOutputDim
        def set_input_info(self, in_info): ...  # optional, dynamic shapes
        def invoke(self, inputs): return [...]
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Sequence

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo


def _first_attr(obj, *names):
    for n in names:
        if hasattr(obj, n):
            return getattr(obj, n)
    return None


@subplugin(FILTER, "python")
class PythonFilter(FilterFramework):
    NAME = "python"

    def __init__(self):
        super().__init__()
        self._obj = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        path = props.model
        if not path or not os.path.isfile(path):
            raise ValueError(f"python: no such script {path!r}")
        spec = importlib.util.spec_from_file_location(
            f"nnstreamer_tpu_pyfilter_{os.path.basename(path).replace('.', '_')}",
            path,
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        cls = _first_attr(mod, "Filter", "CustomFilter")
        if cls is None:
            raise ValueError(
                f"python: {path!r} must define class Filter (or CustomFilter)"
            )
        self._obj = cls(props.custom) if _takes_arg(cls) else cls()

    def close(self) -> None:
        self._obj = None
        super().close()

    def get_model_info(self):
        fin = _first_attr(self._obj, "get_input_info", "getInputDim")
        fout = _first_attr(self._obj, "get_output_info", "getOutputDim")
        return (fin() if fin else None), (fout() if fout else None)

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        f = _first_attr(self._obj, "set_input_info", "setInputDim")
        if f is None:
            return super().set_input_info(in_info)
        return f(in_info)

    def invoke(self, inputs: Sequence) -> List:
        with self.global_stats().measure():
            return list(self._obj.invoke(list(inputs)))


def _takes_arg(cls) -> bool:
    import inspect

    try:
        sig = inspect.signature(cls.__init__)
        return len(sig.parameters) > 1
    except (TypeError, ValueError):
        return False
