"""Custom filter backends: in-process user functions and classes.

Reference: ``tensor_filter_custom.c`` (full vtable from a user .so) and
``tensor_filter_custom_easy.c`` (single function registered from app code,
``include/tensor_filter_custom_easy.h``). These are the test-scaffolding
backbone of the reference (tests/nnstreamer_example custom .so models);
here they are plain Python registrations — the same capability without the
dlopen ceremony.

- :func:`register_custom_easy(name, fn, in_info, out_info)` — the
  custom-easy path: ``fn(list_of_arrays) -> list_of_arrays``; instantiate
  with ``tensor_filter framework=custom-easy model=<name>``.
- :class:`CustomFilterBase` — the full-vtable path: subclass, then
  ``register_custom(name, cls)``; supports dynamic shapes via
  ``set_input_info``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, register_subplugin, subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo

_easy: Dict[str, tuple] = {}
_custom: Dict[str, type] = {}
_lock = threading.Lock()


def register_custom_easy(name: str, fn: Callable[[Sequence[Any]], List[Any]],
                         in_info: TensorsInfo,
                         out_info: TensorsInfo) -> None:
    """Register a single-function model (reference
    ``NNS_custom_easy_register``, tensor_filter_custom_easy.c)."""
    with _lock:
        _easy[name] = (fn, in_info, out_info)


def unregister_custom_easy(name: str) -> bool:
    with _lock:
        return _easy.pop(name, None) is not None


class CustomFilterBase(FilterFramework):
    """Full custom filter: subclass with get_model_info/invoke (reference
    ``NNStreamer_custom_class``, tensor_filter_custom.h)."""

    NAME = "custom"


def register_custom(name: str, cls: type) -> None:
    with _lock:
        _custom[name] = cls


@subplugin(FILTER, "custom-easy")
class CustomEasyFilter(FilterFramework):
    NAME = "custom-easy"

    def __init__(self):
        super().__init__()
        self._fn = None
        self._in = None
        self._out = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        name = props.model
        with _lock:
            entry = _easy.get(name)
        if entry is None:
            raise ValueError(
                f"custom-easy: no registered model {name!r} "
                f"(register_custom_easy first)"
            )
        self._fn, self._in, self._out = entry

    def get_model_info(self):
        return self._in, self._out

    def invoke(self, inputs):
        return list(self._fn(inputs))


@subplugin(FILTER, "custom")
class CustomFilter(FilterFramework):
    """Dispatches to a registered CustomFilterBase subclass by model name."""

    NAME = "custom"

    def __init__(self):
        super().__init__()
        self._impl: Optional[FilterFramework] = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        with _lock:
            cls = _custom.get(props.model)
        if cls is None:
            raise ValueError(f"custom: no registered class {props.model!r}")
        self._impl = cls()
        self._impl.open(props)

    def close(self):
        if self._impl is not None:
            self._impl.close()
            self._impl = None
        super().close()

    def get_model_info(self):
        return self._impl.get_model_info()

    def set_input_info(self, in_info):
        return self._impl.set_input_info(in_info)

    def invoke(self, inputs):
        return self._impl.invoke(inputs)

    def handle_event(self, name, data):
        self._impl.handle_event(name, data)
