"""TFLite filter backend (reference ``tensor_filter_tensorflow_lite.cc``,
1616 LoC — its richest subplugin).

Gated on an available TFLite interpreter (``ai_edge_litert``, standalone
``tflite_runtime``, or full ``tensorflow``); raises a clear error otherwise.
On this stack TFLite runs CPU-only — it exists for drop-in parity with
reference pipelines (``framework=tensorflow-lite model=m.tflite``); the TPU
path is the jax backend."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


def _interpreter_cls():
    try:
        from ai_edge_litert.interpreter import Interpreter  # type: ignore

        return Interpreter
    except ImportError:
        pass
    try:
        from tflite_runtime.interpreter import Interpreter  # type: ignore

        return Interpreter
    except ImportError:
        pass
    try:
        from tensorflow.lite.python.interpreter import Interpreter  # type: ignore

        return Interpreter
    except ImportError:
        return None


@subplugin(FILTER, "tflite")
@subplugin(FILTER, "tensorflow-lite")
class TFLiteFilter(FilterFramework):
    NAME = "tflite"

    def __init__(self):
        super().__init__()
        self._interp = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        cls = _interpreter_cls()
        if cls is None:
            raise RuntimeError(
                "tflite: no TFLite interpreter installed (ai_edge_litert / "
                "tflite_runtime / tensorflow). Use framework=jax for the "
                "TPU-native path."
            )
        num_threads = 1
        for part in (props.custom or "").split(","):
            if part.startswith("num_threads:"):
                num_threads = int(part.split(":", 1)[1])
        self._interp = cls(model_path=props.model, num_threads=num_threads)
        self._interp.allocate_tensors()

    def close(self) -> None:
        self._interp = None
        super().close()

    def _infos(self, details) -> TensorsInfo:
        return TensorsInfo([
            TensorInfo(dim=tuple(reversed([int(x) for x in d["shape"]])),
                       type=TensorType.from_any(d["dtype"]))
            for d in details
        ])

    def get_model_info(self):
        return (self._infos(self._interp.get_input_details()),
                self._infos(self._interp.get_output_details()))

    def invoke(self, inputs: Sequence) -> List:
        ins = self._interp.get_input_details()
        for d, x in zip(ins, inputs):
            self._interp.set_tensor(d["index"],
                                    np.ascontiguousarray(np.asarray(x)))
        with self.global_stats().measure():
            self._interp.invoke()
        return [self._interp.get_tensor(d["index"])
                for d in self._interp.get_output_details()]
