"""PyTorch filter backend (reference ``tensor_filter_pytorch.cc``, 711 LoC).

Loads TorchScript (``.pt``/``.pth`` via ``torch.jit.load``) or pickled
``nn.Module``s and invokes on CPU (this image ships CPU torch; the TPU path
is the jax backend — torch parity exists so reference users can run their
torch models unchanged while migrating)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


@subplugin(FILTER, "torch")
class TorchFilter(FilterFramework):
    NAME = "torch"

    def __init__(self):
        super().__init__()
        self._module = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        import torch

        path = props.model
        try:
            self._module = torch.jit.load(path, map_location="cpu")
        except Exception:
            loaded = torch.load(path, map_location="cpu", weights_only=False)
            if not isinstance(loaded, torch.nn.Module):
                raise ValueError(
                    f"torch: {path!r} is neither TorchScript nor an nn.Module"
                )
            self._module = loaded
        self._module.eval()

    def close(self) -> None:
        self._module = None
        super().close()

    def get_model_info(self):
        return self.props.input_info, self.props.output_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        """Probe output shapes with a zero forward pass (torch has no
        abstract shape eval)."""
        import torch

        zeros = [torch.zeros(i.shape,
                             dtype=getattr(torch, i.type.value))
                 for i in in_info]
        with torch.no_grad():
            out = self._module(*zeros)
        if isinstance(out, torch.Tensor):
            out = [out]
        return TensorsInfo([
            TensorInfo(dim=tuple(reversed(tuple(o.shape))),
                       type=TensorType.from_any(str(o.dtype).split(".")[-1]))
            for o in out
        ])

    def invoke(self, inputs: Sequence) -> List:
        import torch

        tins = [torch.from_numpy(np.ascontiguousarray(np.asarray(x)))
                for x in inputs]
        with self.global_stats().measure(), torch.no_grad():
            out = self._module(*tins)
        if isinstance(out, torch.Tensor):
            out = [out]
        return [o.numpy() for o in out]
