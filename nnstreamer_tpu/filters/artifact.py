"""Compiled-model artifacts for the TPU filter backend.

This closes the reference's core use case — "load an opaque model *file*
and run it on the accelerator" (tensor_filter_tensorflow_lite.cc:154-238
loads any ``.tflite`` byte-for-byte) — the TPU-native way: the artifact is
StableHLO, the portable compiled-model format of the XLA ecosystem, and
the runtime is ``jax.export``.

Three artifact forms are accepted (content-sniffed, any extension):

1. **Serialized ``jax.export.Exported``** — the canonical form, produced
   by :func:`save_artifact` (or any JAX process calling
   ``jax.export.export(...).serialize()``). Self-describing: carries
   input/output avals, target platforms, and the calling convention, so
   ``tensor_filter`` needs no ``input``/``output`` properties.
2. **Raw StableHLO MLIR** (text ``.mlir``/``.stablehlo`` or MLIR
   bytecode) — what non-JAX toolchains emit:
   ``torch_xla.stablehlo.exported_program_to_stablehlo`` for PyTorch and
   TF's ``tf.function`` → MLIR path for SavedModels (see
   docs/model-artifacts.md). The ``@main`` signature provides shapes and
   dtypes; the module is wrapped into an ``Exported`` at load time.
3. **StableHLO portable artifacts** (``stablehlo.serialize_portable_
   artifact`` output) — detected and deserialized before parsing.

Weights ride *inside* the artifact as StableHLO constants (``save_artifact``
closes over params before export), which is exactly the opaque-file
semantic of the reference's model files.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.tensors.types import TensorsInfo

#: MLIR element type ↔ numpy dtype (StableHLO scalar types we support;
#: reference tensor enum parity lives in tensors/types.py).
_MLIR_TO_NP = {
    "f64": np.dtype("float64"),
    "f32": np.dtype("float32"),
    "f16": np.dtype("float16"),
    "i1": np.dtype("bool"),
    "i8": np.dtype("int8"),
    "i16": np.dtype("int16"),
    "i32": np.dtype("int32"),
    "i64": np.dtype("int64"),
    "ui8": np.dtype("uint8"),
    "ui16": np.dtype("uint16"),
    "ui32": np.dtype("uint32"),
    "ui64": np.dtype("uint64"),
}

#: MLIR bytecode magic ("MLïR"); both plain bytecode and StableHLO
#: portable artifacts start with it.
_MLIR_BC_MAGIC = b"ML\xefR"


def _np_from_mlir(elem: str) -> np.dtype:
    if elem == "bf16":
        from nnstreamer_tpu.tensors.types import TensorType

        return TensorType.BFLOAT16.np_dtype
    try:
        return _MLIR_TO_NP[elem]
    except KeyError:
        raise ValueError(
            f"stablehlo artifact: unsupported element type {elem!r}"
        ) from None


# ---------------------------------------------------------------------------
# Export (producer side)
# ---------------------------------------------------------------------------

def save_artifact(path: str, fn: Callable, params: Any = None,
                  in_info: Optional[TensorsInfo] = None,
                  example_inputs: Optional[Sequence[Any]] = None,
                  platforms: Sequence[str] = ("tpu", "cpu")) -> Any:
    """Export ``fn`` (repo convention: ``fn(params, *xs)`` when params is
    not None, else ``fn(*xs)``) as a self-contained compiled-model
    artifact at ``path``.

    Params are closed over, so they become StableHLO constants — the file
    is opaque and complete, like the reference's model files. Input specs
    come from ``in_info`` (caps dims, NNS reversed order) or
    ``example_inputs``. Returns the ``Exported`` (callers can derive
    output info without re-reading the file).
    """
    import jax

    if in_info is not None:
        sds = [jax.ShapeDtypeStruct(i.shape, i.type.np_dtype) for i in in_info]
    elif example_inputs is not None:
        sds = [jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
               for x in example_inputs]
    else:
        raise ValueError("save_artifact: need in_info or example_inputs")

    if params is not None:
        host_params = jax.tree.map(np.asarray, params)

        def wrapped(*xs):
            return fn(host_params, *xs)
    else:
        wrapped = fn

    exp = jax.export.export(jax.jit(wrapped),
                            platforms=list(platforms))(*sds)
    data = bytes(exp.serialize())
    with open(path, "wb") as f:
        f.write(data)
    return exp


# ---------------------------------------------------------------------------
# Ingest (consumer side)
# ---------------------------------------------------------------------------

def _parse_main_signature(data: bytes) -> Tuple[list, list]:
    """Parse a StableHLO module (text or bytecode) and return the
    ``@main`` signature as ([(shape, np_dtype)], [(shape, np_dtype)])."""
    import jaxlib.mlir.ir as ir
    from jax._src.interpreters import mlir as jmlir

    with jmlir.make_ir_context():
        module = ir.Module.parse(data)
        main = None
        for op in module.body.operations:
            if op.operation.name != "func.func":
                continue
            name = ir.StringAttr(op.attributes["sym_name"]).value
            if name == "main" or main is None:
                main = op
            if name == "main":
                break
        if main is None:
            raise ValueError("stablehlo artifact: no func found in module")

        ftype = ir.FunctionType(ir.TypeAttr(main.attributes["function_type"]).value)

        def sig(types):
            out = []
            for t in types:
                rt = ir.RankedTensorType(t)
                shape = tuple(rt.shape)
                if any(d < 0 for d in shape):
                    raise ValueError(
                        "stablehlo artifact: dynamic dims are not supported "
                        f"(got {rt})"
                    )
                out.append((shape, _np_from_mlir(str(rt.element_type))))
            return out

        return sig(ftype.inputs), sig(ftype.results)


def _module_bytes_to_portable(data: bytes) -> Tuple[bytes, bytes]:
    """Normalize raw module ``data`` (MLIR text, MLIR bytecode, or already
    a portable artifact) → (portable_artifact_bytes, parseable_bytes)."""
    import jaxlib.mlir.dialects.stablehlo as shlo

    if data[:4] == _MLIR_BC_MAGIC:
        # Bytecode. A portable artifact deserializes to current-version
        # bytecode; plain bytecode needs serializing to a portable artifact.
        try:
            current = shlo.deserialize_portable_artifact_str(data)
            return data, bytes(current)
        except Exception:
            portable = shlo.serialize_portable_artifact_str(
                data, shlo.get_minimum_version())
            return bytes(portable), data
    # MLIR text.
    portable = shlo.serialize_portable_artifact_str(
        data, shlo.get_minimum_version())
    return bytes(portable), data


def _exported_from_raw_module(data: bytes, platform: str, name: str):
    """Wrap a raw StableHLO module into a ``jax.export.Exported``.

    A template export with identical avals supplies every
    version-dependent field (calling convention, tree defs, shardings);
    only the module bytes are swapped in. The stamped ``platform`` is the
    loader's — raw StableHLO is platform-agnostic.
    """
    import jax
    import jax.numpy as jnp

    portable, parseable = _module_bytes_to_portable(data)
    ins, outs = _parse_main_signature(parseable)
    if not outs:
        raise ValueError("stablehlo artifact: @main has no results")

    def template(*xs):
        zeros = [jnp.zeros(s, d) for s, d in outs]
        return zeros[0] if len(zeros) == 1 else tuple(zeros)

    sds = [jax.ShapeDtypeStruct(s, d) for s, d in ins]
    tmpl = jax.export.export(jax.jit(template), platforms=[platform])(*sds)
    return dataclasses.replace(
        tmpl,
        fun_name=name,
        mlir_module_serialized=portable,
        module_kept_var_idx=tuple(range(len(ins))),
        _get_vjp=None,  # inference artifact: grads must error, not no-op
    )


def _looks_like_mlir(data: bytes) -> bool:
    if data[:4] == _MLIR_BC_MAGIC:
        return True
    head = data[:4096]
    try:
        text = head.decode("utf-8")
    except UnicodeDecodeError:
        return False
    return "module" in text or "func.func" in text


def load_artifact(path: str, platform: Optional[str] = None):
    """Load a compiled-model artifact → ``jax.export.Exported``.

    Content-sniffed: MLIR (text/bytecode/portable) goes down the raw
    route; anything else must be a serialized ``Exported`` — its
    deserialize error is surfaced verbatim (a version-incompatible
    artifact must not be misreported as an MLIR parse failure).
    ``platform`` (default: the runtime's backend) is stamped onto raw
    modules, which carry no platform info."""
    import jax

    with open(path, "rb") as f:
        data = f.read()
    if _looks_like_mlir(data):
        plat = platform or jax.default_backend()
        return _exported_from_raw_module(
            data, plat, os.path.basename(path).rsplit(".", 1)[0])
    try:
        return jax.export.deserialize(data)
    except Exception as e:
        raise ValueError(
            f"cannot load model artifact {path!r}: not StableHLO MLIR, and "
            f"jax.export.deserialize failed: {e}"
        ) from e


def artifact_tensors_info(exp) -> Tuple[TensorsInfo, TensorsInfo]:
    """Derive (in_info, out_info) caps from an Exported's avals —
    artifacts are self-describing, so ``tensor_filter`` needs no
    ``input``/``output`` properties (get_model_info NATIVE mode,
    nnstreamer_plugin_api_filter.h:380). ``from_arrays`` handles rank-0
    avals (scalars map to dim ``(1,)``, never a size-0 info)."""
    return (TensorsInfo.from_arrays(exp.in_avals),
            TensorsInfo.from_arrays(exp.out_avals))


def artifact_entry(path: str, platform: Optional[str] = None) -> dict:
    """Backend entry dict (fn/params/in_info/out_info) for a model file.

    ``fn`` is ``exp.call`` — jittable, fusable into device regions, and
    platform-checked by jax.export itself (a tpu-only artifact run on cpu
    fails with jax's own pointed error)."""
    exp = load_artifact(path, platform)
    in_info, out_info = artifact_tensors_info(exp)

    def fn(*xs):
        out = exp.call(*xs)
        return out if isinstance(out, (list, tuple)) else (out,)

    return dict(fn=fn, params=None, in_info=in_info, out_info=out_info,
                exported=exp)


def export_model(model: str, out_path: str, custom: Optional[str] = None,
                 platforms: Sequence[str] = ("tpu", "cpu"),
                 input_dims: Optional[str] = None,
                 input_types: Optional[str] = None) -> TensorsInfo:
    """Export any backend-loadable model form (registered name, ``.py``
    with ``get_model()``, ``.msgpack`` + factory) to a self-contained
    artifact — the producer half of the opaque-file story (CLI:
    ``nns-launch --export``). ``input_dims`` *overrides* the model's
    declared input info (e.g. to re-specialize the batch size).
    Returns the artifact's output info."""
    from nnstreamer_tpu.filters.jax_backend import resolve_python_model

    entry = resolve_python_model(model, custom)
    if entry is None:
        raise ValueError(f"export: cannot load model {model!r}")

    in_info = entry.get("in_info")
    if input_dims:
        if input_types is None and in_info is not None:
            # dims-only override (e.g. re-specializing batch): keep the
            # model's declared dtypes rather than silently forcing float32
            input_types = ",".join(t.type.value for t in in_info)
        in_info = TensorsInfo.from_str(input_dims, input_types or "float32")
    if in_info is None:
        raise ValueError(
            "export: model has no input info; pass input_dims/input_types "
            "(caps grammar, e.g. '3:224:224:1' 'float32')")

    exp = save_artifact(out_path, entry["fn"], entry.get("params"),
                        in_info=in_info, platforms=platforms)
    _, out_info = artifact_tensors_info(exp)
    return out_info
