"""Native (.so) custom filter backend — the C-ABI extension point.

Reference: ``tensor_filter_custom`` loads user shared objects exposing a C
vtable (gst/nnstreamer/tensor_filter/tensor_filter_custom.c,
include/tensor_filter_custom.h), and the C++ class API wraps the same
contract (include/nnstreamer_cppplugin_api_filter.hh). Here the contract
is ``native/nnstpu_filter.h``: the .so exports
``nnstpu_filter_get_vtable()`` and the backend drives it via ctypes.
Tensors cross as raw host pointers; ctypes releases the GIL during
``invoke``, so native filters run concurrently with the Python pipeline
threads — the reference's native-speed custom-op path, kept native.

``model`` property: path to the .so. ``custom``: opaque option string
passed to the filter's ``open``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType

NNSTPU_MAX_TENSORS = 16
NNSTPU_MAX_RANK = 8
_ABI = 1

_TYPE_ORDER = list(TensorType)


class _CTensorInfo(ctypes.Structure):
    _fields_ = [
        ("rank", ctypes.c_uint32),
        ("dims", ctypes.c_uint32 * NNSTPU_MAX_RANK),
        ("dtype", ctypes.c_int32),
    ]


class _CTensorsInfo(ctypes.Structure):
    _fields_ = [
        ("num_tensors", ctypes.c_uint32),
        ("info", _CTensorInfo * NNSTPU_MAX_TENSORS),
    ]


_PTR = ctypes.c_void_p


class _CVtable(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_int),
        ("open", ctypes.CFUNCTYPE(_PTR, ctypes.c_char_p)),
        ("close", ctypes.CFUNCTYPE(None, _PTR)),
        ("get_model_info", ctypes.CFUNCTYPE(
            ctypes.c_int, _PTR, ctypes.POINTER(_CTensorsInfo),
            ctypes.POINTER(_CTensorsInfo))),
        ("set_input_info", ctypes.CFUNCTYPE(
            ctypes.c_int, _PTR, ctypes.POINTER(_CTensorsInfo),
            ctypes.POINTER(_CTensorsInfo))),
        ("invoke", ctypes.CFUNCTYPE(
            ctypes.c_int, _PTR, ctypes.POINTER(_PTR),
            ctypes.POINTER(_PTR))),
    ]


def _to_c_info(info: TensorsInfo) -> _CTensorsInfo:
    c = _CTensorsInfo()
    c.num_tensors = len(info)
    for i, ti in enumerate(info):
        shape = ti.shape  # numpy order
        c.info[i].rank = len(shape)
        for d, s in enumerate(shape):
            c.info[i].dims[d] = s
        c.info[i].dtype = _TYPE_ORDER.index(ti.type)
    return c


def _from_c_info(c: _CTensorsInfo) -> Optional[TensorsInfo]:
    if c.num_tensors == 0:
        return None
    infos = []
    for i in range(c.num_tensors):
        ci = c.info[i]
        shape = tuple(ci.dims[d] for d in range(ci.rank))
        infos.append(TensorInfo(dim=tuple(reversed(shape)),
                                type=_TYPE_ORDER[ci.dtype]))
    return TensorsInfo(infos)


@subplugin(FILTER, "native")
class NativeFilter(FilterFramework):
    NAME = "native"
    KEEP_ON_DEVICE = False

    def __init__(self):
        super().__init__()
        self._dll: Optional[ctypes.CDLL] = None
        self._vt: Optional[_CVtable] = None
        self._handle: Optional[int] = None
        self._out_info: Optional[TensorsInfo] = None
        self._in_info: Optional[TensorsInfo] = None

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        path = props.model
        if not path or not os.path.isfile(path):
            raise ValueError(f"native: model must be a .so path, got "
                             f"{path!r}")
        self._dll = ctypes.CDLL(os.path.abspath(path))
        getter = self._dll.nnstpu_filter_get_vtable
        getter.restype = ctypes.POINTER(_CVtable)
        self._vt = getter().contents
        if self._vt.abi_version != _ABI:
            raise RuntimeError(
                f"native: {path} has filter ABI {self._vt.abi_version}, "
                f"runtime expects {_ABI}")
        custom = (props.custom or "").encode()
        self._handle = self._vt.open(custom if custom else None)
        if not self._handle:
            raise RuntimeError(f"native: {path} open() failed")

    def close(self) -> None:
        if self._vt is not None and self._handle:
            self._vt.close(self._handle)
        self._dll = self._vt = self._handle = None
        super().close()

    def get_model_info(self):
        cin, cout = _CTensorsInfo(), _CTensorsInfo()
        rc = self._vt.get_model_info(self._handle, ctypes.byref(cin),
                                     ctypes.byref(cout))
        if rc != 0:
            raise RuntimeError(f"native: get_model_info failed ({rc})")
        self._in_info = _from_c_info(cin)
        self._out_info = _from_c_info(cout)
        return self._in_info, self._out_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        cin = _to_c_info(in_info)
        cout = _CTensorsInfo()
        if not self._vt.set_input_info:
            raise RuntimeError("native: filter has no set_input_info and "
                               "no static output info")
        rc = self._vt.set_input_info(self._handle, ctypes.byref(cin),
                                     ctypes.byref(cout))
        if rc != 0:
            raise RuntimeError(f"native: set_input_info failed ({rc})")
        self._in_info = in_info
        self._out_info = _from_c_info(cout)
        if self._out_info is None:
            raise RuntimeError("native: set_input_info returned no output "
                               "info")
        return self._out_info

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        if self._out_info is None:
            # static-info filters may skip set_input_info; derive now
            self.set_input_info(TensorsInfo.from_arrays(list(inputs)))
        ins = [np.ascontiguousarray(x) for x in inputs]
        outs = [np.empty(i.shape, i.type.np_dtype) for i in self._out_info]
        in_ptrs = (_PTR * len(ins))(
            *[x.ctypes.data_as(_PTR).value for x in ins])
        out_ptrs = (_PTR * len(outs))(
            *[x.ctypes.data_as(_PTR).value for x in outs])
        with self.global_stats().measure():
            rc = self._vt.invoke(self._handle, in_ptrs, out_ptrs)
        if rc != 0:
            raise RuntimeError(f"native: invoke failed ({rc})")
        return outs
