"""Pipeline-as-filter backend (reference ``tensor_filter_mediapipe.cc``,
373 LoC: an entire MediaPipe graph runs behind the filter vtable).

Here the nested "graph" is one of our own pipelines: the ``model``
property is a pipeline description (inline, or a ``.pipeline`` file)
containing an ``appsrc name=in`` and a ``tensor_sink name=out``::

    tensor_filter framework=pipeline \
        model="appsrc name=in ! tensor_transform mode=arithmetic \
               option=mul:2.0 ! tensor_sink name=out"

``open`` parses and starts the inner pipeline once; each ``invoke``
pushes the input frame into ``in`` and blocks until ``out`` emits the
result, so the nested pipeline (including any jax filters it contains,
with their own region fusion) is a single element of the outer one.
Frames stay ordered because the inner pipeline is itself order-preserving.

This is also the composition primitive the reference gets from
"composite models" pages: sub-pipelines become reusable filter units.
"""

from __future__ import annotations

import os
import queue
from typing import Any, List, Optional, Sequence

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo

log = get_logger("filters.pipeline")


@subplugin(FILTER, "pipeline")
class PipelineFilter(FilterFramework):
    """A nested pipeline behind the filter vtable."""

    NAME = "pipeline"

    #: seconds to wait for the inner pipeline to yield one result
    INVOKE_TIMEOUT = 120.0

    def __init__(self):
        super().__init__()
        self._pipe = None
        self._src = None
        self._results: "queue.Queue" = queue.Queue()

    def open(self, props: FilterProperties) -> None:
        super().open(props)
        desc = props.model or ""
        if os.path.isfile(desc):
            with open(desc, "r", encoding="utf-8") as f:
                desc = f.read()
        if "appsrc" not in desc or "tensor_sink" not in desc:
            raise ValueError(
                "pipeline: description needs 'appsrc name=in' and "
                "'tensor_sink name=out'"
            )
        from nnstreamer_tpu.pipeline.parse import parse_launch

        self._pipe = parse_launch(" ".join(desc.split()))
        self._src = self._pipe.get("in")
        sink = self._pipe.get("out")
        sink.connect(self._results.put)
        self._pipe.start()

    def close(self) -> None:
        if self._pipe is not None:
            try:
                self._src.end_of_stream()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("inner pipeline EOS on close failed: %s", e)
            self._pipe.stop()
        self._pipe = self._src = None
        super().close()

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        # probe the inner pipeline with one zero frame of the negotiated
        # shape; its output defines our output caps.
        import numpy as np

        zeros = [np.zeros(t.shape, t.type.np_dtype) for t in in_info]
        outs = self.invoke(zeros)
        return TensorsInfo.from_arrays(outs)

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        if self._pipe is None:
            raise RuntimeError("pipeline: not opened")
        with self.global_stats().measure():
            self._src.push(list(inputs))
            try:
                buf = self._results.get(timeout=self.INVOKE_TIMEOUT)
            except queue.Empty:
                # surface an inner-pipeline error if that's why we starved
                msg = self._pipe.pop_message(timeout=0)
                while msg is not None and msg.kind != "error":
                    msg = self._pipe.pop_message(timeout=0)
                if msg is not None:
                    raise RuntimeError(
                        f"pipeline: inner pipeline error: {msg.error}"
                    )
                raise RuntimeError(
                    "pipeline: inner pipeline produced no result "
                    f"within {self.INVOKE_TIMEOUT}s"
                )
            return list(buf.tensors)
