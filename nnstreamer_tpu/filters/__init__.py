"""L4/L5 — filter-framework API and backend subplugins."""

from nnstreamer_tpu.filters.api import (  # noqa: F401
    FilterFramework,
    FilterProperties,
    shared_model_get,
    shared_model_insert,
    shared_model_remove,
)
from nnstreamer_tpu.filters.custom import register_custom_easy  # noqa: F401
