"""The JAX/XLA filter backend — the flagship TPU inference path.

This plays the role the reference's vendor-runtime subplugins play
(tensor_filter_tensorflow_lite.cc / _tensorrt.cc / _edgetpu.cc ...): it
implements the FilterFramework vtable by compiling the model with XLA and
invoking it on the accelerator. Design points (TPU-first, not a port):

- **One jitted program per (model, input shapes/dtypes).** ``jax.jit``
  caches compiled executables; caps negotiation uses ``jax.eval_shape``
  (abstract, no compile) so probing shapes never triggers compilation —
  the reference warns exactly about this (nnstreamer_plugin_api_filter.h:
  357-361).
- **Params live in HBM once.** ``open()`` device_puts params; every invoke
  reuses them (the reference's TFLiteInterpreter tensor-ptr caching,
  tensor_filter_tensorflow_lite.cc:198, becomes "weights are resident").
- **Async dispatch.** invoke() returns device arrays without blocking; the
  pipeline overlaps host work with device execution; only a sink that
  needs bytes blocks.
- **Software-device mode for CI.** accelerator "true:cpu" runs the same
  code on CPU XLA (the reference EdgeTPU ``device_type:dummy`` pattern).
- **Sharded invoke.** custom option ``sharding:<axis>`` shards the batch
  dim over a device mesh with ``NamedSharding`` — XLA inserts ICI
  collectives (see ``parallel.mesh``).

Model forms accepted (``model`` property):
- a name registered via :func:`register_jax_model` (apps, tests);
- ``<file>.py`` exporting ``get_model()`` → ``fn`` or ``(fn, params)``;
- ``<file>.msgpack`` flax-serialized params, with ``custom=module:<name>``
  naming a model factory from ``nnstreamer_tpu.models``;
- **compiled-model artifacts** (``.jaxexp``/``.stablehlo``/``.mlir``/
  ``.mlirbc``): serialized ``jax.export.Exported`` or raw StableHLO
  modules, weights baked in as constants — the opaque-file load the
  reference's vendor subplugins provide
  (tensor_filter_tensorflow_lite.cc:154-238); see ``filters/artifact.py``
  and docs/model-artifacts.md.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.filters.api import (
    FilterFramework,
    FilterProperties,
    shared_model_get,
    shared_model_insert,
)
from nnstreamer_tpu.config import ARTIFACT_EXTS
from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors import memory as _memory
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType

log = get_logger("jax-filter")

_registered: Dict[str, dict] = {}
_reg_lock = threading.Lock()


def register_jax_model(name: str, fn: Callable, params: Any = None,
                       in_info: Optional[TensorsInfo] = None,
                       out_info: Optional[TensorsInfo] = None) -> None:
    """Register a jittable model under ``name``.

    ``fn(params, *inputs) -> output(s)`` when params is not None, else
    ``fn(*inputs) -> output(s)``. Shapes may be left None — they are then
    derived from negotiated input caps via ``jax.eval_shape``.
    """
    with _reg_lock:
        _registered[name] = dict(fn=fn, params=params, in_info=in_info,
                                 out_info=out_info)


def unregister_jax_model(name: str) -> bool:
    with _reg_lock:
        return _registered.pop(name, None) is not None


def is_jax_model_registered(name: str) -> bool:
    with _reg_lock:
        return name in _registered


def _parse_accelerator(acc: Optional[str]) -> Optional[str]:
    """Reference accelerator grammar "true:tpu" / "false" / "true:cpu"
    (nnstreamer_plugin_api_filter.h:547-568) → jax platform or None."""
    if not acc:
        return None
    parts = acc.split(":")
    if parts[0].strip().lower() in ("false", "0", "no"):
        return "cpu"
    return parts[1].strip().lower() if len(parts) > 1 else None


def _load_py_model(path: str) -> dict:
    spec = importlib.util.spec_from_file_location(
        f"nnstreamer_tpu_model_{os.path.basename(path).replace('.', '_')}",
        path,
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    if not hasattr(mod, "get_model"):
        raise ValueError(f"jax model file {path!r} must define get_model()")
    got = mod.get_model()
    if isinstance(got, tuple):
        fn, params = got
    else:
        fn, params = got, None
    return dict(fn=fn, params=params,
                in_info=getattr(mod, "IN_INFO", None),
                out_info=getattr(mod, "OUT_INFO", None))


def _load_msgpack_model(path: str, custom: Optional[str]) -> dict:
    from flax import serialization

    factory_name = None
    for part in (custom or "").split(","):
        if part.startswith("module:"):
            factory_name = part.split(":", 1)[1]
    if factory_name is None:
        raise ValueError(
            "jax: .msgpack model needs custom=module:<models factory> "
            "(e.g. custom=module:mobilenet_v2)"
        )
    from nnstreamer_tpu import models as model_zoo

    factory = getattr(model_zoo, factory_name, None)
    if factory is None:
        raise ValueError(f"jax: unknown model factory {factory_name!r}")
    fn, params_template, in_info, out_info = factory()
    with open(path, "rb") as f:
        params = serialization.from_bytes(params_template, f.read())
    return dict(fn=fn, params=params, in_info=in_info, out_info=out_info)


def resolve_python_model(model: str, custom: Optional[str]) -> Optional[dict]:
    """Resolve the Python-authored model forms (registered name, ``.py``
    with ``get_model()``, ``.msgpack`` + factory) to an entry dict, or
    None if ``model`` is none of them. Shared by the filter and the
    artifact exporter so ``--export`` accepts exactly what the filter
    loads."""
    name = model.split(":", 1)[1] if model.startswith("registered:") else model
    with _reg_lock:
        if name in _registered:
            return dict(_registered[name])
    if model.endswith(".py") and os.path.isfile(model):
        return _load_py_model(model)
    if model.endswith(".msgpack") and os.path.isfile(model):
        return _load_msgpack_model(model, custom)
    return None


@subplugin(FILTER, "jax")
class JaxFilter(FilterFramework):
    NAME = "jax"
    KEEP_ON_DEVICE = True

    def __init__(self):
        super().__init__()
        self._fn: Optional[Callable] = None
        self._params: Any = None
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None
        self._jitted: Optional[Callable] = None
        self._device = None
        self._sharding = None
        #: parsed ``mesh=`` serving plan (parallel/serve.py MeshPlan);
        #: None = single-device (or NNSTPU_MESH=0 killed the mesh)
        self._mesh_plan = None
        #: residency unit holding the device params when an HBM budget
        #: is active (tensors/memory.py); None = plain resident weights.
        #: Under a mesh this is the PRIMARY of a per-shard unit group
        #: and _resident_keys lists every shard key for retirement.
        self._resident = None
        self._resident_keys: List[str] = []

    # -- lifecycle -----------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        super().open(props)
        import jax

        platform = _parse_accelerator(props.accelerator)
        self._explicit_platform = platform
        try:
            self._device = jax.devices(platform)[0] if platform else \
                jax.devices()[0]
        except RuntimeError as e:
            raise RuntimeError(
                f"jax: no {platform or 'default'} device available: {e}"
            ) from e

        model = props.model
        if not model:
            raise ValueError("jax: model not set")

        entry = None
        if props.shared_key:
            entry = shared_model_get(props.shared_key)
        if entry is None:
            entry = self._load(model, props)
            if props.shared_key:
                entry = shared_model_insert(props.shared_key, entry)
        # bump the trace token only when the model *function* actually
        # changed — fused regions key their jit cache on it, so a
        # params-only reload swaps consts without an XLA recompile.
        # (_last_fn survives close(): reload is close()+open(), and the
        # identity must be compared across that gap)
        if entry["fn"] is not getattr(self, "_last_fn", None):
            self._fn_token = getattr(self, "_fn_token", 0) + 1
            self._last_fn = entry["fn"]
        self._fn = entry["fn"]
        self._params = entry["params"]
        self._in_info = props.input_info or entry.get("in_info")
        self._out_info = props.output_info or entry.get("out_info")

        for part in (props.custom or "").split(","):
            if part.startswith("sharding:"):
                from nnstreamer_tpu.parallel.mesh import batch_sharding

                self._sharding = batch_sharding(part.split(":", 1)[1])

        # mesh= property (elements/filter.py): the first-class multi-chip
        # serving plane. A MeshPlan is BatchSharding-compatible, so the
        # invoke path below shards the batch over dp and replicates the
        # weights exactly like custom=sharding: — plus the fused region
        # compiles the whole-graph program across the mesh. Kill switch:
        # NNSTPU_MESH=0 ignores the property and keeps this filter
        # byte-identical to the single-device path.
        self._mesh_plan = None
        mesh_spec = getattr(props, "mesh", None)
        if mesh_spec:
            from nnstreamer_tpu.parallel import serve as _serve

            if _serve.mesh_enabled():
                self._mesh_plan = _serve.get_mesh_plan(mesh_spec)
                self._sharding = self._mesh_plan
            else:
                log.info("mesh=%s requested but NNSTPU_MESH=0: "
                         "single-device path", mesh_spec)

        if self._params is not None:
            tgt = self._sharding.replicated() if self._sharding else self._device
            acct = _memory.ACTIVE
            if acct is not None:
                # budgeted mode: the weights become an evictable residency
                # unit — self._params stays the HOST pytree (shapes for
                # eval_shape), the device copy is fetched per invoke via
                # the unit so an eviction genuinely frees the HBM
                self._resident = self._register_resident(
                    acct, f"jax:{id(self)}", self._params, tgt, str(model))
                self._resident.value()  # initial load, under the budget
            else:
                self._params = jax.device_put(self._params, tgt)
        self._jitted = None  # (re)built lazily per dtype/shape set

    def _load(self, model: str, props: FilterProperties) -> dict:
        entry = resolve_python_model(model, props.custom)
        if entry is not None:
            return entry
        if model.endswith(ARTIFACT_EXTS) and os.path.isfile(model):
            from nnstreamer_tpu.filters.artifact import artifact_entry

            return artifact_entry(model, platform=self._device.platform)
        if (model.endswith(".pb") and os.path.isfile(model)) or (
                os.path.isdir(model)
                and os.path.isfile(os.path.join(model, "saved_model.pb"))):
            # the reference runs these via libtensorflow
            # (tensor_filter_tensorflow.cc:785); the TPU-native route
            # stages the graph through TF's XLA bridge to StableHLO at
            # open() when tensorflow is importable (filters/tf_backend),
            # else falls back to the offline-export recipe
            from nnstreamer_tpu.filters.tf_backend import (
                have_tensorflow,
                tf_model_entry,
            )

            if have_tensorflow():
                return tf_model_entry(model, custom=props.custom,
                                      props_in_info=props.input_info)
            raise ValueError(
                f"jax: {model!r} is a TensorFlow GraphDef/SavedModel and "
                "tensorflow is not importable here; export it to a "
                "StableHLO artifact first (see docs/model-artifacts.md, "
                "'TensorFlow models') and load the .stablehlo file instead"
            )
        raise ValueError(
            f"jax: cannot load model {model!r} (not registered, not a .py/"
            f".msgpack file, not a {'/'.join(ARTIFACT_EXTS)} artifact)"
        )

    def _register_resident(self, acct, key_base: str, host_params: Any,
                           tgt, label: str):
        """Register the weights with the HBM accountant and return the
        primary residency unit. Single-device: one unit. Under a mesh:
        ONE UNIT PER SHARD in a load/evict group — the replicated
        placement puts a full copy on every chip, so each shard unit
        carries the full pytree bytes and ``nns_mem_used_bytes`` sums to
        the real multi-chip HBM footprint. ``_resident_keys`` records
        every key so close()/install_weights() retire the whole group."""
        import jax

        nbytes = _memory.pytree_nbytes(host_params)

        def _load(hp, _tgt=tgt):
            return jax.device_put(hp, _tgt)

        plan = self._mesh_plan
        if plan is None:
            self._resident_keys = [key_base]
            return acct.residency.register(
                key=key_base, host_value=host_params, nbytes=nbytes,
                loader=_load, label=label)
        units = [acct.residency.register(
            key=f"{key_base}:shard{k}", host_value=host_params,
            nbytes=nbytes, loader=_load, label=f"{label}#shard{k}",
            group=key_base) for k in range(plan.shard_count)]
        self._resident_keys = [u.key for u in units]
        return units[0]

    def install_weights(self, params: Any, epoch: int = 0) -> Dict[str, Any]:
        """In-place params swap for ``Pipeline.swap_model`` (serving
        continuity): the model *function* is unchanged, so the fused
        region's trace key is unchanged and the swap is a consts swap —
        no XLA recompile, no ``_fn_token`` bump.

        Under an HBM budget the new params register as a NEW residency
        unit keyed by the swap epoch and the old epoch's unit retires in
        the same step — without the retire every swap would leak
        ``nns_mem_used_bytes`` until process exit."""
        import jax

        if self._fn is None:
            raise RuntimeError("jax: install_weights before open()")
        tgt = self._sharding.replicated() if self._sharding else self._device
        acct = _memory.ACTIVE
        out: Dict[str, Any] = {"residency": None, "retired": None}
        if acct is not None:
            old = self._resident
            old_keys = list(self._resident_keys)
            new_key = f"jax:{id(self)}:e{int(epoch)}"
            self._resident = self._register_resident(
                acct, new_key, params, tgt,
                f"{self.props.model}@e{int(epoch)}")
            self._params = params
            if old is not None:
                # retire the WHOLE previous epoch — under a mesh that is
                # one unit per shard, and leaving any behind would leak
                # a full per-chip weight copy in nns_mem_used_bytes
                for k in old_keys:
                    acct.residency.unregister(k)
                out["retired"] = old.key
            out["residency"] = new_key
            self._resident.value()  # load now, under the budget
        else:
            self._params = jax.device_put(params, tgt)
        self._jitted = None  # the pytree structure may have changed
        return out

    def close(self) -> None:
        if self._resident is not None:
            acct = _memory.ACTIVE
            if acct is not None:
                for k in (self._resident_keys or [self._resident.key]):
                    acct.residency.unregister(k)
            self._resident = None
            self._resident_keys = []
        self._fn = self._params = self._jitted = None
        super().close()

    # -- model info ----------------------------------------------------------
    def get_model_info(self):
        return self._in_info, self._out_info

    def _call(self, params, *inputs):
        out = self._fn(params, *inputs) if params is not None else \
            self._fn(*inputs)
        if isinstance(out, (list, tuple)):
            return list(out)
        return [out]

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        """Derive output shapes abstractly (no compile)."""
        import jax

        self._in_info = in_info
        shaped_in = [jax.ShapeDtypeStruct(i.shape, i.type.np_dtype)
                     for i in in_info]
        params_shape = jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(np.shape(p), np.asarray(p).dtype)
            if not hasattr(p, "aval") else
            jax.ShapeDtypeStruct(p.shape, p.dtype),
            self._params,
        ) if self._params is not None else None
        out = jax.eval_shape(self._call, params_shape, *shaped_in)
        self._out_info = TensorsInfo([
            TensorInfo(dim=tuple(reversed(o.shape)),
                       type=TensorType.from_any(o.dtype))
            for o in out
        ])
        return self._out_info

    # -- region fusion (pipeline/fuse.py) ------------------------------------
    def device_stage(self):
        """Expose the model as a pure fused-region stage; params ride as the
        stage consts so hot reload swaps them without recompiling.

        Not fusible with legacy ``custom=sharding:`` batch sharding or an
        explicitly-requested platform: invoke() places inputs with
        NamedSharding / onto the chosen device, and a plain fused jit
        would silently drop that placement. A ``mesh=`` plan IS fusible —
        the stage advertises the mesh spec and the region compiles the
        whole-graph program with the plan's shardings (pipeline/fuse.py).
        Not fusible while an HBM budget holds the weights as an evictable
        residency unit — fused consts would pin the evicted device copy
        alive and the eviction would free nothing."""
        if self._fn is None or self._resident is not None or \
                getattr(self, "_explicit_platform", None):
            return None
        if self._sharding is not None and self._mesh_plan is None:
            return None
        from nnstreamer_tpu.pipeline.fuse import DeviceStage

        def fn(params, tensors):
            return self._call(params, *tensors)

        return DeviceStage(consts=self._params, fn=fn,
                           key=("jax", id(self), self._fn_token),
                           mesh=self._mesh_plan.spec
                           if self._mesh_plan is not None else None)

    # -- hot path ------------------------------------------------------------
    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        import jax

        if self._jitted is None:
            self._jitted = jax.jit(self._call)
        dev_inputs = []
        if self._mesh_plan is not None:
            # mesh invoke: batch-shard over dp via the serving plane —
            # already-matched device arrays move ZERO bytes, a sharding
            # mismatch re-places AND counts nns_reshard_bytes_total
            from nnstreamer_tpu.parallel import serve as _serve

            dev_inputs = [_serve.place_batch(x, self._mesh_plan)
                          for x in inputs]
        else:
            for x in inputs:
                if isinstance(x, jax.Array) and self._sharding is None:
                    dev_inputs.append(x)
                else:
                    tgt = self._sharding.batched() if self._sharding \
                        else self._device
                    dev_inputs.append(jax.device_put(x, tgt))  # nns-lint: disable=NNS113 -- transient invoke input; the frame's bytes are tracked upstream at to_device/upload_many
        # budgeted mode routes through the residency unit: an evicted
        # model prefetches back in here (LRU touch per invoke)
        params = self._resident.value() if self._resident is not None \
            else self._params
        with self.global_stats().measure():
            out = self._jitted(params, *dev_inputs)
        return out
