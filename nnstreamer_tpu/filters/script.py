"""Inline-script filter backend (reference ``tensor_filter_lua.cc``, 566
LoC: filters defined by a script string or file, no compiled model needed).

The reference embeds a Lua interpreter and runs the script per frame on the
CPU. The TPU-native take: the script is a tiny Python/jax.numpy program
that is **traced once and jitted**, so a "scripted filter" costs the same
as a compiled one — it fuses into a single XLA program and runs on the
MXU/VPU rather than an interpreter.

Script protocol: inputs are bound as ``x0..xN`` (and ``x`` = ``x0``),
namespace has ``jnp``/``jax``/``lax``/``np``; outputs are whatever the
script assigns to ``y0..yN`` (or ``y``)::

    tensor_filter framework=script model="y = jnp.tanh(x) * 2.0"
    tensor_filter framework=script model=my_filter.jaxs   # same, from file

**Data-dependent control flow** (reference lua scripts branch per frame)
has two homes:

- *structured ops, jitted* (default mode): ``cond`` / ``while_loop`` /
  ``fori_loop`` / ``switch`` / ``select`` are pre-bound in the script
  namespace (``lax.*``), so a per-frame branch compiles into the XLA
  program::

      y = cond(jnp.mean(x) > 0.5, lambda a: a * 2.0,
               lambda a: a * 0.5, x.astype(jnp.float32))

- ``custom=mode:host`` — *interpreted per frame on the host*, the
  reference's lua semantics exactly: arbitrary imperative Python
  (``if float(np.mean(x)) > 0.5: ...``) over numpy arrays, no tracing
  rules. The same structured-ops names are bound to host shims with
  identical semantics, and 64-bit numpy promotions are narrowed back to
  the 32-bit widths jax produces, so a script written with
  ``cond``/``while_loop`` produces identical outputs AND negotiates the
  same output dtypes in both modes
  (``tests/test_filter_backends_extra.py``). Caps negotiation executes
  a host-mode script once on an all-ones probe frame.

Default mode runs under jit tracing: no raw Python control flow on traced
values, static shapes — the same rules as any jitted function. One
specialization is compiled per negotiated input shape-set and cached.
"""

from __future__ import annotations

import os
import re
import types
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.filters.api import (
    FilterFramework,
    FilterProperties,
    parse_custom,
)
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


_Y_RE = re.compile(r"^y(\d+)$")


def _host_cond(pred, true_fn, false_fn, *operands):
    return true_fn(*operands) if pred else false_fn(*operands)


def _host_while(cond_fn, body_fn, init):
    val = init
    while cond_fn(val):
        val = body_fn(val)
    return val


def _host_fori(lo, hi, body_fn, init):
    val = init
    for i in range(int(lo), int(hi)):
        val = body_fn(i, val)
    return val


def _host_switch(index, branches, *operands):
    i = min(max(int(index), 0), len(branches) - 1)  # lax.switch clamps
    return branches[i](*operands)


#: structured control-flow surface bound into every script namespace —
#: lax ops under jit (device mode), semantically-identical host shims in
#: mode=host, so one script runs in both modes with the same outputs
_DEVICE_OPS = dict(cond=jax.lax.cond, while_loop=jax.lax.while_loop,
                   fori_loop=jax.lax.fori_loop, switch=jax.lax.switch,
                   select=jnp.where)
_HOST_OPS = dict(cond=_host_cond, while_loop=_host_while,
                 fori_loop=_host_fori, switch=_host_switch,
                 select=np.where)


def _host_lax():
    """Fresh `lax.*` shim namespace per filter open: a script that
    rebinds a shim must not leak the mutation into every other
    host-mode filter in the process."""
    return types.SimpleNamespace(**_HOST_OPS)

#: numpy promotes to 64-bit where jax (x64 disabled) stays 32-bit; host
#: outputs are narrowed to the device-mode widths so one script
#: negotiates the SAME output dtypes in both modes
_HOST_DTYPE_NARROW = {np.dtype(np.float64): np.float32,
                      np.dtype(np.int64): np.int32,
                      np.dtype(np.uint64): np.uint32,
                      np.dtype(np.complex128): np.complex64}


def _narrow_host(arr: np.ndarray) -> np.ndarray:
    tgt = _HOST_DTYPE_NARROW.get(arr.dtype)
    return arr.astype(tgt) if tgt is not None else arr


@subplugin(FILTER, "script")
class ScriptFilter(FilterFramework):
    """Jit-compiled expression/script filters (``custom=mode:host`` for
    per-frame interpreted execution, lua-parity semantics)."""

    NAME = "script"
    KEEP_ON_DEVICE = True

    def __init__(self):
        super().__init__()
        self._src: Optional[str] = None
        self._code = None
        self._jitted = None
        self._host_mode = False
        self._in_info: Optional[TensorsInfo] = None
        #: host mode: negotiated output (shape, dtype) pairs — the
        #: interpreter has no tracer to freeze shapes, so invoke()
        #: validates each frame's outputs against what negotiation
        #: announced (a data-dependent shape fails HERE, loudly, not in
        #: a downstream element sized off stale caps)
        self._out_spec = None

    # -- vtable --------------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        super().open(props)
        src = props.model or ""
        if os.path.isfile(src):
            with open(src, "r", encoding="utf-8") as f:
                src = f.read()
        if not src.strip():
            raise ValueError("script: empty script (model property)")
        mode = parse_custom(props.custom).get("mode", "device")
        if mode not in ("device", "host"):
            raise ValueError(
                f"script: mode must be 'device' or 'host', got {mode!r}")
        self._host_mode = mode == "host"
        # reset per open(): a reused instance must not validate frames
        # against a PREVIOUS script's negotiated output spec
        self._out_spec = None
        # set on BOTH branches: a reused instance re-opened in device
        # mode must win back the on-device fast path
        self.KEEP_ON_DEVICE = not self._host_mode
        self._src = src
        self._code = compile(src, "<tensor_filter_script>", "exec")
        host_lax = _host_lax()

        def run(*inputs):
            if self._host_mode:
                # per-frame interpreter: plain numpy + host control-flow
                # shims; jnp aliases numpy and `lax` exposes the same
                # shims so device-flavored scripts (lax.cond spelling
                # included) run unchanged
                ns: Dict[str, Any] = {
                    "np": np, "jnp": np, "lax": host_lax, **_HOST_OPS}
            else:
                ns = {"jnp": jnp, "jax": jax, "lax": jax.lax, "np": jnp,
                      **_DEVICE_OPS}
            for i, x in enumerate(inputs):
                ns[f"x{i}"] = x
            ns["x"] = inputs[0]
            ns["n_inputs"] = len(inputs)
            exec(self._code, ns)  # device mode: traced once under jit
            if self._host_mode:
                def asarray(v):
                    return _narrow_host(np.asarray(v))
            else:
                asarray = jnp.asarray
            if "y" in ns and not any(_Y_RE.match(k) for k in ns):
                return [asarray(ns["y"])]
            outs = sorted(
                ((int(_Y_RE.match(k).group(1)), v) for k, v in ns.items()
                 if _Y_RE.match(k)),
                key=lambda kv: kv[0],
            )
            if not outs:
                raise ValueError(
                    "script: script must assign y (or y0..yN)"
                )
            return [asarray(v) for _, v in outs]

        self._run = run
        self._jitted = None if self._host_mode else \
            jax.jit(lambda *xs: tuple(run(*xs)))

    def close(self) -> None:
        self._src = self._code = self._jitted = None
        super().close()

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        self._in_info = in_info
        if self._host_mode:
            # the interpreter has no tracer: probe shapes with one real
            # execution. Ones, not zeros — value-dependent loops whose
            # progress rides on nonzero data (doubling until a bound,
            # mean-gated branches) must not spin forever on an all-zero
            # probe. Negotiation DOES run the script once in this mode.
            dummies = [np.ones(t.shape, t.type.np_dtype) for t in in_info]
            outs = self._run(*dummies)
            self._out_spec = [(tuple(o.shape), np.dtype(o.dtype))
                              for o in outs]
        else:
            specs = [
                jax.ShapeDtypeStruct(t.shape, t.type.np_dtype)
                for t in in_info
            ]
            outs = jax.eval_shape(lambda *xs: tuple(self._run(*xs)),
                                  *specs)
        return TensorsInfo([
            TensorInfo(dim=tuple(reversed(o.shape)),
                       type=TensorType.from_any(np.dtype(o.dtype)))
            for o in outs
        ])

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        with self.global_stats().measure():
            if self._host_mode:
                outs = self._run(*[np.asarray(x) for x in inputs])
                if self._out_spec is not None:
                    if len(outs) != len(self._out_spec):
                        raise ValueError(
                            f"script: host script produced {len(outs)} "
                            f"outputs, negotiated "
                            f"{len(self._out_spec)}")
                    for i, (o, (shape, dt)) in enumerate(
                            zip(outs, self._out_spec)):
                        if tuple(o.shape) != shape or o.dtype != dt:
                            raise ValueError(
                                f"script: host output {i} is "
                                f"{tuple(o.shape)}:{o.dtype}, caps "
                                f"negotiated {shape}:{dt} — "
                                f"data-dependent output shapes are not "
                                f"streamable")
                return list(outs)
            return list(self._jitted(*[jnp.asarray(x) for x in inputs]))
