"""Inline-script filter backend (reference ``tensor_filter_lua.cc``, 566
LoC: filters defined by a script string or file, no compiled model needed).

The reference embeds a Lua interpreter and runs the script per frame on the
CPU. The TPU-native take: the script is a tiny Python/jax.numpy program
that is **traced once and jitted**, so a "scripted filter" costs the same
as a compiled one — it fuses into a single XLA program and runs on the
MXU/VPU rather than an interpreter.

Script protocol: inputs are bound as ``x0..xN`` (and ``x`` = ``x0``),
namespace has ``jnp``/``jax``/``lax``/``np``; outputs are whatever the
script assigns to ``y0..yN`` (or ``y``)::

    tensor_filter framework=script model="y = jnp.tanh(x) * 2.0"
    tensor_filter framework=script model=my_filter.jaxs   # same, from file

The script runs under jit tracing: no data-dependent Python control flow
(use ``lax.cond``/``lax.select``), static shapes — the same rules as any
jitted function. One specialization is compiled per negotiated input
shape-set and cached.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.registry import FILTER, subplugin
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


_Y_RE = re.compile(r"^y(\d+)$")


@subplugin(FILTER, "script")
class ScriptFilter(FilterFramework):
    """Jit-compiled expression/script filters."""

    NAME = "script"
    KEEP_ON_DEVICE = True

    def __init__(self):
        super().__init__()
        self._src: Optional[str] = None
        self._code = None
        self._jitted = None
        self._in_info: Optional[TensorsInfo] = None

    # -- vtable --------------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        super().open(props)
        src = props.model or ""
        if os.path.isfile(src):
            with open(src, "r", encoding="utf-8") as f:
                src = f.read()
        if not src.strip():
            raise ValueError("script: empty script (model property)")
        self._src = src
        self._code = compile(src, "<tensor_filter_script>", "exec")

        def run(*inputs):
            ns: Dict[str, Any] = {
                "jnp": jnp, "jax": jax, "lax": jax.lax, "np": jnp,
            }
            for i, x in enumerate(inputs):
                ns[f"x{i}"] = x
            ns["x"] = inputs[0]
            ns["n_inputs"] = len(inputs)
            exec(self._code, ns)  # traced once under jit, not per frame
            if "y" in ns and not any(_Y_RE.match(k) for k in ns):
                return [jnp.asarray(ns["y"])]
            outs = sorted(
                ((int(_Y_RE.match(k).group(1)), v) for k, v in ns.items()
                 if _Y_RE.match(k)),
                key=lambda kv: kv[0],
            )
            if not outs:
                raise ValueError(
                    "script: script must assign y (or y0..yN)"
                )
            return [jnp.asarray(v) for _, v in outs]

        self._run = run
        self._jitted = jax.jit(lambda *xs: tuple(run(*xs)))

    def close(self) -> None:
        self._src = self._code = self._jitted = None
        super().close()

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        self._in_info = in_info
        dummies = [
            jax.ShapeDtypeStruct(t.shape, t.type.np_dtype) for t in in_info
        ]
        outs = jax.eval_shape(lambda *xs: tuple(self._run(*xs)), *dummies)
        return TensorsInfo([
            TensorInfo(dim=tuple(reversed(o.shape)),
                       type=TensorType.from_any(np.dtype(o.dtype)))
            for o in outs
        ])

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        with self.global_stats().measure():
            return list(self._jitted(*[jnp.asarray(x) for x in inputs]))
