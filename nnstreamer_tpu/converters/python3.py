"""python3 converter — user-script media→tensor converters (reference
``tensor_converter/tensor_converter_python3.cc``, 404 LoC). The script
defines::

    class Converter:
        def get_out_config(self, caps): ...   # optional
        def convert(self, buf, in_caps): ...

Two ways to use it:

- app registration: ``load_python_converter("myconv", "/path/s.py")``,
  then ``tensor_converter mode=custom-code:myconv``;
- conf-driven: set ``[converter] python3_script`` (or env
  ``NNSTREAMER_TPU_CONVERTER_PYTHON3_SCRIPT``) and use
  ``tensor_converter mode=custom-code:python3`` — the reference resolves
  its python subplugin paths through nnstreamer.ini the same way.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading

from nnstreamer_tpu.registry import CONVERTER, register_subplugin, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _load_script(path: str, tag: str):
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    spec = importlib.util.spec_from_file_location(
        f"nnstreamer_tpu_pyconv_{tag}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    cls = getattr(mod, "Converter", None)
    if cls is None:
        raise ValueError(f"{path!r} must define class Converter")
    return cls()


def load_python_converter(name: str, path: str) -> None:
    """Load a converter script and register it under ``name`` (apps call
    this; tensor_converter mode=custom-code:<name> then finds it)."""
    register_subplugin(CONVERTER, name, _load_script(path, name))


@subplugin(CONVERTER, "python3")
class Python3Converter:
    """Conf-driven script converter: the script path comes from
    ``[converter] python3_script`` (env override supported)."""

    def __init__(self):
        self._obj = None
        self._key = None  # (path, mtime) — in-place edits reload
        self._lock = threading.Lock()

    def _load(self):
        from nnstreamer_tpu.config import get_conf

        path = get_conf().get("converter", "python3_script")
        if not path:
            raise ValueError(
                "python3 converter: set [converter] python3_script in the "
                "conf (or NNSTREAMER_TPU_CONVERTER_PYTHON3_SCRIPT), or "
                "register a script with load_python_converter()")
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            # script vanished/unreadable after a successful load: keep
            # serving the loaded object (pre-reload-support behavior)
            with self._lock:
                if self._obj is not None and path == self._key[0]:
                    return self._obj
            raise FileNotFoundError(path)
        key = (path, mtime)
        with self._lock:
            if self._obj is None or key != self._key:
                self._obj = _load_script(path, "conf")
                self._key = key
            return self._obj

    def get_out_config(self, caps):
        obj = self._load()
        if hasattr(obj, "get_out_config"):
            return obj.get_out_config(caps)
        return None

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        return self._load().convert(buf, in_caps)
