"""python3 converter — user-script media→tensor converters (reference
``tensor_converter/tensor_converter_python3.cc``, 404 LoC). The script
(named by the converter mode string after the colon, or via conf) defines::

    class Converter:
        def get_out_config(self, caps): ...   # optional
        def convert(self, buf, in_caps): ...
"""

from __future__ import annotations

import importlib.util
import os
import sys

from nnstreamer_tpu.registry import CONVERTER, register_subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def load_python_converter(name: str, path: str) -> None:
    """Load a converter script and register it under ``name`` (apps call
    this; tensor_converter mode=custom-code:<name> then finds it)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    spec = importlib.util.spec_from_file_location(
        f"nnstreamer_tpu_pyconv_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    cls = getattr(mod, "Converter", None)
    if cls is None:
        raise ValueError(f"{path!r} must define class Converter")
    register_subplugin(CONVERTER, name, cls())
