"""L5 converter subplugins (reference ext/nnstreamer/tensor_converter/):
parse serialized payloads back into tensor streams. Protocol (duck-typed):
``get_out_config(caps) -> TensorsConfig | None`` and
``convert(buf, in_caps) -> TensorBuffer``."""
