"""flexbuf converter — serialized flex stream → tensors (reference
``tensor_converter/tensor_converter_flexbuf.cc``, 188 LoC). Inverse of
``decoders.flexbuf``."""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.decoders.flexbuf import decode_flex
from nnstreamer_tpu.registry import CONVERTER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(CONVERTER, "flexbuf")
class FlexBufConverter:
    def get_out_config(self, caps):
        return None  # per-buffer shapes

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_flex(blob)
        return out.replace(pts=buf.pts if out.pts is None else out.pts,
                           meta=dict(buf.meta))
