"""flexbuf converter — FlexBuffers byte stream → tensors (reference
``tensor_converter/tensor_converter_flexbuf.cc``, 188 LoC). Inverse of
``decoders.flexbuf``; parses the reference wire layout. The
framework-native compact framing stays available as
``mode=nnstpu-flex``."""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.decoders.flexbuf import decode_flex, decode_flexbuf
from nnstreamer_tpu.registry import CONVERTER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(CONVERTER, "flexbuf")
class FlexBufConverter:
    """Reference-format FlexBuffers payload → tensors."""

    def get_out_config(self, caps):
        return None  # per-buffer shapes

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_flexbuf(blob)
        # keep the decoded wire meta (framerate/format/tensor_names) and
        # overlay the incoming buffer's own meta on top
        return out.replace(pts=buf.pts, meta={**out.meta, **buf.meta})


@subplugin(CONVERTER, "nnstpu-flex")
class NnstpuFlexConverter:
    """Framework-native compact flex framing → tensors."""

    def get_out_config(self, caps):
        return None

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_flex(blob)
        return out.replace(pts=buf.pts if out.pts is None else out.pts,
                           meta=dict(buf.meta))
