"""protobuf converter — serialized Tensors message → tensors (reference
``tensor_converter/tensor_converter_protobuf.cc``, 89 LoC). Inverse of
``decoders.protobuf_codec``."""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.decoders.protobuf_codec import decode_protobuf
from nnstreamer_tpu.registry import CONVERTER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(CONVERTER, "protobuf")
class ProtobufConverter:
    def get_out_config(self, caps):
        return None

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_protobuf(blob)
        # keep the decoded wire meta (framerate/format/tensor_names) and
        # overlay the incoming buffer's own meta on top
        return out.replace(pts=buf.pts, meta={**out.meta, **buf.meta})
