"""nnstreamer_tpu — a TPU-native streaming-inference framework.

A brand-new framework with the capabilities of NNStreamer (reference:
DaeyangCho/nnstreamer): typed multi-tensor streams flowing through composable
pipeline elements (convert, transform, filter/infer, decode, mux/demux/merge/
split, aggregate, rate-control, conditional branch, recurrence), pluggable
model backends behind a stable filter API, runtime latency/throughput
instrumentation, and distributed offload — re-designed idiomatically for TPU:

- the compute path is JAX/XLA: filters jit their models, tensors stay
  device-resident (``jax.Array`` in HBM) as they flow between elements;
- batching across sources (tensor_mux) becomes one batched XLA invoke;
- multi-chip scaling uses ``jax.sharding.Mesh`` + XLA collectives over ICI,
  not hand-rolled transports;
- distributed offload (tensor_query equivalent) runs a framed TCP / gRPC
  front-end over DCN feeding the sharded on-device path.

Layer map (mirrors SURVEY.md §1):

- L1 ``tensors``   — tensor type system, caps, buffers, flexible/sparse meta
- L2 ``config`` / ``registry`` — ini+env config, subplugin registries
- L3 ``elements`` / ``pipeline`` — stream elements and the pipeline core
- L4 ``filters.api`` — the filter-framework vtable (FilterFramework)
- L5 ``filters.*`` / ``decoders`` / ``converters`` — subplugins
- L6 ``query`` — distributed client/server/pub-sub
- L7 ``single`` / ``parse`` — pipeline-less invoke + gst-launch-style CLI
"""

__version__ = "0.1.0"

# before everything else: with NNSTPU_LOCKGRAPH set, the lock-order
# witness must patch the threading factories ahead of every module that
# creates locks at import time (obs/__init__ arms it as ITS first
# statement; with the env unset this import changes nothing)
import nnstreamer_tpu.obs  # noqa: E402,F401

from nnstreamer_tpu.tensors.types import (  # noqa: E402,F401
    TensorType,
    TensorFormat,
    TensorInfo,
    TensorsInfo,
    TensorsConfig,
    NNS_TENSOR_RANK_LIMIT,
    NNS_TENSOR_SIZE_LIMIT,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer  # noqa: F401
from nnstreamer_tpu.pipeline.pipeline import Pipeline  # noqa: F401
from nnstreamer_tpu.pipeline.parse import parse_launch  # noqa: F401
