"""Subplugin registries — name → implementation per subplugin kind.

Reference: ``nnstreamer_subplugin.c`` keeps one hash per type
{FILTER, DECODER, CONVERTER} with lazy dlopen discovery
(``get_subplugin``:138, ``register_subplugin``:222). Here the same contract:

- :func:`register_subplugin` / decorator :func:`subplugin` — explicit
  registration (what the reference's .so constructors do);
- :func:`get_subplugin` — lookup with lazy discovery: on a miss we import
  the built-in module that provides the name, then any user search paths
  from config (``[filter] path=...`` etc. — the dlopen analog is importing
  ``nnstreamer_tpu_<kind>_<name>.py`` from those paths), then installed
  entry points if available.

Also registers ELEMENT factories (pipeline/parse.py builds pipelines by
element name, like gst's element registry, registerer/nnstreamer.c:85-116).
"""

from __future__ import annotations

import importlib
import importlib.util
import os
import sys
import threading
from typing import Any, Callable, Dict, Optional

from nnstreamer_tpu.config import get_conf
from nnstreamer_tpu.log import get_logger

log = get_logger("registry")

FILTER = "filter"
DECODER = "decoder"
CONVERTER = "converter"
ELEMENT = "element"

_KINDS = (FILTER, DECODER, CONVERTER, ELEMENT)
_registry: Dict[str, Dict[str, Any]] = {k: {} for k in _KINDS}
_lock = threading.RLock()

#: name → module that provides it, for lazy built-in discovery.
_BUILTIN_PROVIDERS: Dict[str, Dict[str, str]] = {
    FILTER: {
        "jax": "nnstreamer_tpu.filters.jax_backend",
        "torch": "nnstreamer_tpu.filters.torch_backend",
        "python": "nnstreamer_tpu.filters.python_class",
        "custom": "nnstreamer_tpu.filters.custom",
        "custom-easy": "nnstreamer_tpu.filters.custom",
        "tflite": "nnstreamer_tpu.filters.tflite_backend",
        "tensorflow-lite": "nnstreamer_tpu.filters.tflite_backend",
        "tensorflow": "nnstreamer_tpu.filters.tf_backend",
        "native": "nnstreamer_tpu.filters.native_filter",
        "script": "nnstreamer_tpu.filters.script",
        "pipeline": "nnstreamer_tpu.filters.pipeline_filter",
        "transformers": "nnstreamer_tpu.filters.transformers_backend",
    },
    DECODER: {
        "image_labeling": "nnstreamer_tpu.decoders.image_labeling",
        "bounding_boxes": "nnstreamer_tpu.decoders.bounding_boxes",
        "pose_estimation": "nnstreamer_tpu.decoders.pose_estimation",
        "image_segment": "nnstreamer_tpu.decoders.image_segment",
        "direct_video": "nnstreamer_tpu.decoders.direct_video",
        "octet_stream": "nnstreamer_tpu.decoders.octet_stream",
        "flexbuf": "nnstreamer_tpu.decoders.flexbuf",
        "nnstpu-flex": "nnstreamer_tpu.decoders.flexbuf",
        "protobuf": "nnstreamer_tpu.decoders.protobuf_codec",
        "flatbuf": "nnstreamer_tpu.decoders.flatbuf_codec",
        "python3": "nnstreamer_tpu.decoders.python3",
    },
    CONVERTER: {
        "flexbuf": "nnstreamer_tpu.converters.flexbuf",
        "nnstpu-flex": "nnstreamer_tpu.converters.flexbuf",
        "protobuf": "nnstreamer_tpu.converters.protobuf_codec",
        "flatbuf": "nnstreamer_tpu.decoders.flatbuf_codec",
        "python3": "nnstreamer_tpu.converters.python3",
    },
    ELEMENT: {},  # populated by nnstreamer_tpu.elements at import
}

_ELEMENTS_MODULE = "nnstreamer_tpu.elements"


def register_subplugin(kind: str, name: str, impl: Any,
                       replace: bool = True) -> None:
    """Register ``impl`` under (kind, name). Reference
    ``register_subplugin`` (nnstreamer_subplugin.c:222)."""
    if kind not in _KINDS:
        raise ValueError(f"unknown subplugin kind {kind!r}")
    with _lock:
        if name in _registry[kind] and not replace:
            raise ValueError(f"{kind} subplugin {name!r} already registered")
        _registry[kind][name] = impl


def unregister_subplugin(kind: str, name: str) -> bool:
    with _lock:
        return _registry[kind].pop(name, None) is not None


def subplugin(kind: str, name: str) -> Callable:
    """Class/function decorator form of :func:`register_subplugin`."""

    def deco(obj):
        register_subplugin(kind, name, obj)
        return obj

    return deco


def _try_import(module: str) -> bool:
    try:
        importlib.import_module(module)
        return True
    except ImportError as e:
        log.debug("lazy import of %s failed: %s", module, e)
        return False


def external_subplugin_filename(kind: str, name: str) -> str:
    """The on-disk filename the external search expects — shared with the
    ``--scaffold`` codegen so the two can never drift."""
    return f"nnstreamer_tpu_{kind}_{name}.py"


def _search_external(kind: str, name: str) -> None:
    """Load ``nnstreamer_tpu_<kind>_<name>.py`` from configured search paths
    (the dlopen-from-conf-paths analog, nnstreamer_subplugin.c:107-135)."""
    fname = external_subplugin_filename(kind, name)
    for path in get_conf().subplugin_paths(kind):
        full = os.path.join(path, fname)
        if os.path.isfile(full):
            spec = importlib.util.spec_from_file_location(
                f"nnstreamer_tpu_ext.{kind}.{name}", full
            )
            mod = importlib.util.module_from_spec(spec)
            sys.modules[spec.name] = mod
            spec.loader.exec_module(mod)  # module registers itself on import
            return


def get_subplugin(kind: str, name: str) -> Optional[Any]:
    """Look up a subplugin, lazily discovering built-ins and externals.
    Reference ``get_subplugin`` (nnstreamer_subplugin.c:138)."""
    with _lock:
        if name in _registry[kind]:
            return _registry[kind][name]
    if kind == ELEMENT:
        _try_import(_ELEMENTS_MODULE)
    provider = _BUILTIN_PROVIDERS.get(kind, {}).get(name)
    if provider:
        _try_import(provider)
    with _lock:
        if name not in _registry[kind]:
            _lock.release()
            try:
                _search_external(kind, name)
            finally:
                _lock.acquire()
        return _registry[kind].get(name)


def registered_names(kind: str) -> list:
    """All known names for a kind: explicitly registered plus lazily
    discoverable built-ins (for tooling like confchk)."""
    with _lock:
        names = set(_registry[kind])
    names.update(_BUILTIN_PROVIDERS.get(kind, {}))
    return sorted(names)


def list_subplugins(kind: str) -> Dict[str, Any]:
    with _lock:
        return dict(_registry[kind])
