"""tensor_demux — one multi-tensor frame → N streams.

Reference: ``gst/nnstreamer/elements/gsttensordemux.c`` (658 LoC).
``tensorpick`` selects which tensors go to which src pad
(e.g. ``tensorpick=0,1:2`` → pad0 gets tensor 0, pad1 gets tensors 1+2).
"""

from __future__ import annotations

from typing import List, Optional

from nnstreamer_tpu.pipeline.element import CapsEvent, Element, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.types import TensorsConfig, TensorsInfo


@subplugin(ELEMENT, "tensor_demux")
class TensorDemux(Element):
    ELEMENT_NAME = "tensor_demux"
    DEVICE_PASSTHROUGH = True  # routes tensor subsets by reference
    PROPERTIES = {**Element.PROPERTIES, "tensorpick": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._pick: Optional[List[List[int]]] = None
        self._in_cfg = None

    def _get_pick(self, num_tensors: int) -> List[List[int]]:
        if self._pick is None:
            spec = self.get_property("tensorpick")
            if spec:
                self._pick = [
                    [int(i) for i in group.split(":")]
                    for group in str(spec).split(",")
                ]
            else:
                self._pick = [[i] for i in range(num_tensors)]
        return self._pick

    def _ensure_pads(self, n: int):
        while len(self.srcpads) < n:
            self.add_src_pad(f"src_{len(self.srcpads)}")

    def request_src_pad(self):
        return self.add_src_pad(f"src_{len(self.srcpads)}")

    def link(self, downstream):
        # src pads are request-style: allocate one per link if all are taken
        if all(p.peer is not None for p in self.srcpads):
            self.request_src_pad()
        return super().link(downstream)

    def chain(self, pad, buf):
        pick = self._get_pick(buf.num_tensors)
        self._ensure_pads(len(pick))
        ret = FlowReturn.OK
        for pad_i, idxs in enumerate(pick):
            sp = self.srcpads[pad_i]
            if sp.caps is None and self._in_cfg is not None and \
                    self._in_cfg.info.is_valid():
                infos = TensorsInfo([self._in_cfg.info[i] for i in idxs])
                sp.set_caps(TensorsConfig(info=infos,
                                          rate=self._in_cfg.rate).to_caps())
            out = buf.with_tensors([buf.tensors[i] for i in idxs])
            r = sp.push(out)
            if r is FlowReturn.EOS:
                ret = r
        return ret

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            self._in_cfg = TensorsConfig.from_caps(event.caps)
            if self._in_cfg.info.is_valid():
                pick = self._get_pick(len(self._in_cfg.info))
                self._ensure_pads(len(pick))
            return
        super().sink_event(pad, event)
