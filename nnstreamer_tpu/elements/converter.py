"""tensor_converter — media streams → ``other/tensors``.

Reference: ``gst/nnstreamer/elements/gsttensorconverter.c`` (2307 LoC):
converts video/audio/text/octet/flexible streams into typed tensor frames,
re-chunking with a GstAdapter (``_gst_tensor_converter_chain_chunk``:937),
handling ``frames-per-tensor`` batching, and delegating unknown media types
to external converter subplugins (``registerExternalConverter``:2185).

Only converter (and decoder) know media semantics — every other element is
semantics-agnostic (Documentation/component-description.md:15). Dim
conventions match the reference: video → (C, W, H, N-frames); audio →
(channels, samples); text/octet → per ``input-dim``/``input-type``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.pipeline.element import CapsEvent, Element, Event, Pad
from nnstreamer_tpu.registry import CONVERTER, ELEMENT, get_subplugin, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
    TensorsConfig,
    TensorsInfo,
    TensorType,
)

_VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRA": 4, "GRAY8": 1}
_AUDIO_TYPES = {"S8": "int8", "U8": "uint8", "S16LE": "int16",
                "U16LE": "uint16", "S32LE": "int32", "U32LE": "uint32",
                "F32LE": "float32", "F64LE": "float64"}


@subplugin(ELEMENT, "tensor_converter")
class TensorConverter(Element):
    ELEMENT_NAME = "tensor_converter"
    PROPERTIES = {
        **Element.PROPERTIES,
        "frames_per_tensor": 1,
        "input_dim": None,   # for octet/text streams: e.g. "3:224:224:1"
        "input_type": None,  # e.g. "uint8"
        "format": "static",  # output format: static | flexible
        "mode": None,        # "custom-code:<registered-converter-name>"
        "set_timestamp": True,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._in_caps: Optional[Caps] = None
        self._out_config: Optional[TensorsConfig] = None
        self._pending = bytearray()  # adapter for octet re-chunking
        self._frame_acc: list = []   # adapter for frames-per-tensor batching
        self._custom = None
        self._frame_idx = 0

    def reorder_safe(self):
        # per-buffer conversion regimes (frames_per_tensor=1, no octet
        # re-chunking via input_dim, no custom adapter) map each input
        # buffer to exactly one output buffer with no cross-frame state
        # (_pending/_frame_acc stay empty, _frame_idx is unused when the
        # upstream source stamps pts) — replicable across lanes. The
        # batching/re-chunking regimes fold multiple frames and must see
        # the stream in order.
        return (int(self.get_property("frames_per_tensor") or 1) <= 1
                and not self.get_property("mode")
                and not self.get_property("input_dim"))

    # -- negotiation ---------------------------------------------------------
    def transform_caps(self, pad, caps):
        self._in_caps = caps
        self._out_config = self._derive_config(caps)
        if self._out_config is None:
            return None  # flexible/custom: announce on first buffer
        return self._out_config.to_caps()

    def _derive_config(self, caps: Caps) -> Optional[TensorsConfig]:
        mode = self.get_property("mode")
        if mode:  # custom converter owns the output config
            name = mode.split(":", 1)[1] if ":" in mode else mode
            impl = get_subplugin(CONVERTER, name)
            if impl is None:
                raise ValueError(f"tensor_converter: no converter subplugin "
                                 f"{name!r}")
            self._custom = impl() if isinstance(impl, type) else impl
            out = getattr(self._custom, "get_out_config", lambda c: None)(caps)
            return out
        rate = Fraction.parse(caps.get("framerate", "0/1"))
        fpt = int(self.get_property("frames_per_tensor"))
        if caps.name == "video/x-raw":
            ch = _VIDEO_CHANNELS[caps.get("format", "RGB")]
            w, h = int(caps["width"]), int(caps["height"])
            info = TensorInfo(dim=(ch, w, h, fpt), type=TensorType.UINT8)
            return TensorsConfig(info=TensorsInfo([info]), rate=rate)
        if caps.name == "audio/x-raw":
            t = TensorType(_AUDIO_TYPES[caps.get("format", "S16LE")])
            ch = int(caps.get("channels", 1))
            info = TensorInfo(dim=(ch, fpt), type=t)
            return TensorsConfig(info=TensorsInfo([info]), rate=rate)
        if caps.name in ("application/octet-stream", "text/x-raw"):
            dim = self.get_property("input_dim")
            typ = self.get_property("input_type") or "uint8"
            if caps.name == "text/x-raw" and dim is None:
                raise ValueError(
                    "tensor_converter: text streams need input-dim "
                    "(reference requires 'input-dim' for text, "
                    "gsttensorconverter.c)"
                )
            if dim is None:
                return None  # per-buffer shape → flexible output
            info = TensorInfo.from_str(dim, typ)
            return TensorsConfig(info=TensorsInfo([info]), rate=rate)
        if caps.name in ("other/tensor", "other/tensors"):
            cfg = TensorsConfig.from_caps(caps)
            if cfg.format is not TensorFormat.STATIC:
                return None  # flexible input: emit static per-buffer
            return cfg
        raise ValueError(f"tensor_converter: unsupported media {caps.name!r} "
                         f"(use mode=custom-code:<name>)")

    # -- dataflow ------------------------------------------------------------
    def chain(self, pad, buf):
        if self._custom is not None:
            out = self._custom.convert(buf, self._in_caps)
            return self._emit(out)
        caps_name = self._in_caps.name if self._in_caps else MEDIA_DEFAULT
        if caps_name == "video/x-raw":
            return self._chain_video(buf)
        if caps_name == "audio/x-raw":
            return self._chain_audio(buf)
        if caps_name in ("application/octet-stream", "text/x-raw"):
            return self._chain_octet(buf)
        return self._emit(buf)  # tensor passthrough (possibly flex→static)

    def _emit(self, buf: TensorBuffer):
        if self.srcpad.caps is None:
            cfg = TensorsConfig.from_arrays(buf.tensors)
            if self.get_property("format") == "flexible":
                cfg = TensorsConfig(format=TensorFormat.FLEXIBLE)
            self.srcpad.set_caps(cfg.to_caps())
        if self.get_property("set_timestamp") and buf.pts is None:
            rate = self._out_config.rate if self._out_config else Fraction(0, 1)
            dur = rate.frame_duration_ns
            buf = buf.replace(pts=self._frame_idx * dur if dur else
                              TensorBuffer.wall_clock_pts())
        self._frame_idx += 1
        return self.srcpad.push(buf)

    def _chain_video(self, buf):
        """video frame (H,W,C) → tensor shape (N,H,W,C) == dim (C,W,H,N).

        The reference strips stride-4 row padding here
        (gsttensorconverter.c width-stride handling); our sources produce
        packed arrays so only the frames-per-tensor batching remains.
        """
        frame = np.asarray(buf[0])
        if frame.ndim == 2:
            frame = frame[:, :, None]
        fpt = int(self.get_property("frames_per_tensor"))
        if fpt <= 1:
            return self._emit(buf.with_tensors([frame[None]]))
        self._frame_acc.append((frame, buf))
        if len(self._frame_acc) < fpt:
            return None
        acc = [f for f, _ in self._frame_acc]
        if all(f.shape == acc[0].shape and f.dtype == acc[0].dtype
               for f in acc):
            # stack into a recycled aligned staging buffer
            # (tensors/pool.py) — this is the converter's one per-output
            # host allocation on the batched ingest path
            from nnstreamer_tpu.tensors.pool import get_pool

            frames = get_pool().acquire((len(acc),) + acc[0].shape,
                                        acc[0].dtype)
            np.stack(acc, axis=0, out=frames)
        else:
            frames = np.stack(acc, axis=0)
        first = self._frame_acc[0][1]
        self._frame_acc.clear()
        return self._emit(first.with_tensors([frames]))

    def _chain_audio(self, buf):
        samples = np.asarray(buf[0])  # (S, ch)
        if samples.ndim == 1:
            samples = samples[:, None]
        fpt = int(self.get_property("frames_per_tensor"))
        want = fpt if fpt > 1 else samples.shape[0]
        # adapter: re-chunk to `want` samples per tensor
        self._frame_acc.append((samples, buf))
        total = sum(s.shape[0] for s, _ in self._frame_acc)
        if total < want:
            return None
        cat = np.concatenate([s for s, _ in self._frame_acc], axis=0)
        first = self._frame_acc[0][1]
        self._frame_acc.clear()
        ret = None
        while cat.shape[0] >= want:
            chunk, cat = cat[:want], cat[want:]
            ret = self._emit(first.with_tensors([chunk]))
        if cat.shape[0]:
            self._frame_acc.append((cat, first))
        return ret

    def _chain_octet(self, buf):
        dim = self.get_property("input_dim")
        typ = TensorType.from_any(self.get_property("input_type") or "uint8")
        raw = np.ascontiguousarray(np.asarray(buf[0])).tobytes()
        if dim is None:
            arr = np.frombuffer(raw, dtype=typ.np_dtype)
            return self._emit(buf.with_tensors([arr]))
        info = TensorInfo.from_str(dim, typ.value)
        self._pending.extend(raw)
        frame_size = info.size
        ret = None
        while len(self._pending) >= frame_size:
            chunk = bytes(self._pending[:frame_size])
            del self._pending[:frame_size]
            arr = np.frombuffer(chunk, dtype=typ.np_dtype).reshape(info.shape)
            ret = self._emit(buf.with_tensors([arr]))
        return ret

    def handle_eos(self):
        self._pending.clear()
        self._frame_acc.clear()


MEDIA_DEFAULT = "application/octet-stream"
