"""tensor_transform — elementwise ops on tensor streams, XLA-fused.

Reference: ``gst/nnstreamer/elements/gsttensortransform.c`` (1867 LoC) with
modes ``dimchg, typecast, arithmetic, transpose, stand, clamp``
(tensor_transform.h:57-84), SIMD-accelerated via orc (transform-orc.orc,
``acceleration`` property).

TPU-first design: each configured transform compiles to one jitted XLA
callable (cached per input shape/dtype), so when the input is a device
``jax.Array`` the op runs on-device and XLA fuses it with neighboring
filter programs — the orc-SIMD role, played by the XLA compiler.
``acceleration=false`` falls back to numpy for tiny host-side streams where
dispatch overhead would dominate.

Option grammars follow the reference:
  mode=typecast   option=float32
  mode=arithmetic option=typecast:float32,add:-127.5,div:127.5
  mode=transpose  option=1:0:2:3          (dim-index permutation)
  mode=dimchg     option=0:2              (move dim position 0 → 2)
  mode=stand      option=default[:per-channel] | dc-average[:per-channel]
  mode=clamp      option=min:max
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer, is_device_array
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsConfig, TensorType


def _parse_arith(option: str) -> List[Tuple[str, Optional[float], Optional[str]]]:
    """Parse the arithmetic op chain: [(op, value|None, dtype|None), ...]."""
    ops = []
    for part in option.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"arithmetic option item needs ':': {part!r}")
        op, val = part.split(":", 1)
        op = op.strip().lower()
        if op == "typecast":
            ops.append((op, None, val.strip()))
        elif op in ("add", "sub", "mul", "div"):
            ops.append((op, float(val), None))
        else:
            raise ValueError(f"unknown arithmetic op {op!r}")
    return ops


class _TransformSpec:
    """Parsed (mode, option) → pure function on one array, jax or numpy."""

    def __init__(self, mode: str, option: str, accelerate: bool):
        self.mode = mode
        self.option = option
        self.accelerate = accelerate
        self._jitted: Optional[Callable] = None

    # -- the pure op, written against an array namespace (np or jnp) --------
    def apply(self, xp, x):
        mode, option = self.mode, self.option
        if mode == "typecast":
            return x.astype(TensorType.from_any(option).np_dtype)
        if mode == "arithmetic":
            for op, val, dtype in _parse_arith(option):
                if op == "typecast":
                    x = x.astype(TensorType.from_any(dtype).np_dtype)
                elif op == "add":
                    x = x + val
                elif op == "sub":
                    x = x - val
                elif op == "mul":
                    x = x * val
                elif op == "div":
                    x = x / val
            return x
        if mode == "transpose":
            # option indexes dims (innermost-first); numpy axes are reversed
            perm_dim = [int(p) for p in option.split(":")]
            rank = x.ndim
            perm_dim = perm_dim[:rank] + list(range(len(perm_dim), rank))
            axes = [rank - 1 - p for p in reversed(perm_dim)]
            return xp.transpose(x, axes)
        if mode == "dimchg":
            frm, to = (int(p) for p in option.split(":"))
            rank = x.ndim
            src_ax, dst_ax = rank - 1 - frm, rank - 1 - to
            return xp.moveaxis(x, src_ax, dst_ax)
        if mode == "stand":
            parts = option.split(":")
            kind = parts[0] or "default"
            per_ch = len(parts) > 1 and parts[1] == "per-channel"
            # channel = innermost dim == last numpy axis
            axes = tuple(range(x.ndim - 1)) if per_ch else None
            xf = x.astype(np.float32)
            mean = xf.mean(axis=axes, keepdims=per_ch)
            if kind == "default":
                std = xf.std(axis=axes, keepdims=per_ch)
                return (xf - mean) / (std + 1e-10)
            if kind == "dc-average":
                return xf - mean
            raise ValueError(f"unknown stand option {kind!r}")
        if mode == "clamp":
            lo, hi = (float(p) for p in option.split(":"))
            # typed clamp: bounds saturate into the tensor's own dtype so
            # the output dtype is preserved (reference gst_tensor_data
            # typed math — clamping a uint8 stream must not promote to
            # float, and option=-1:300 must saturate, not overflow)
            dt = np.dtype(x.dtype)
            if dt.kind in "iu":
                info = np.iinfo(dt)
                # exact integer arithmetic: float64 rounding of iinfo.max
                # (int64/uint64) would overflow the cast below
                lo = info.min if lo <= info.min else \
                    min(int(lo), info.max)
                hi = info.max if hi >= info.max else \
                    max(int(hi), info.min)
            return xp.clip(x, xp.asarray(lo, dtype=x.dtype),
                           xp.asarray(hi, dtype=x.dtype))
        raise ValueError(f"unknown transform mode {mode!r}")

    def __call__(self, x):
        if self.accelerate or is_device_array(x):
            import jax
            import jax.numpy as jnp

            if self._jitted is None:
                self._jitted = jax.jit(functools.partial(self.apply, jnp))
            return self._jitted(x)
        return self.apply(np, np.asarray(x))

    def out_info(self, info: TensorInfo) -> TensorInfo:
        """Static shape/type inference for caps negotiation (uses jax's
        shape-only abstract eval — no data, no compile)."""
        import jax
        import jax.numpy as jnp

        shaped = jax.eval_shape(
            functools.partial(self.apply, jnp),
            jax.ShapeDtypeStruct(info.shape, info.type.np_dtype),
        )
        return TensorInfo(dim=tuple(reversed(shaped.shape)),
                          type=TensorType.from_any(shaped.dtype))


@subplugin(ELEMENT, "tensor_transform")
class TensorTransform(Element):
    ELEMENT_NAME = "tensor_transform"
    DEVICE_PASSTHROUGH = True  # device inputs take the jitted path
    # every output is a pure function of (input buffer, properties); the
    # compiled-spec cache in _get_spec is caps-keyed, not frame-keyed
    REORDER_SAFE = True
    PROPERTIES = {
        **Element.PROPERTIES,
        "mode": None,
        "option": "",
        "acceleration": True,
        "apply": None,  # comma list of tensor indices; default all
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._spec: Optional[_TransformSpec] = None

    def _get_spec(self) -> _TransformSpec:
        mode = self.get_property("mode")
        if mode is None:
            raise ValueError("tensor_transform: mode not set")
        if self._spec is None or (self._spec.mode, self._spec.option) != (
            mode, self.get_property("option")
        ):
            self._spec = _TransformSpec(mode, self.get_property("option"),
                                        bool(self.get_property("acceleration")))
        return self._spec

    def _apply_indices(self, n: int) -> List[int]:
        sel = self.get_property("apply")
        if not sel:
            return list(range(n))
        return [int(i) for i in str(sel).split(",")]

    def transform_caps(self, pad, caps):
        try:
            cfg = TensorsConfig.from_caps(caps)
        except ValueError:
            return caps
        if not cfg.info.is_valid():
            return caps
        spec = self._get_spec()
        idx = set(self._apply_indices(len(cfg.info)))
        new_infos = [
            spec.out_info(info) if i in idx else info
            for i, info in enumerate(cfg.info)
        ]
        from nnstreamer_tpu.tensors.types import TensorsInfo

        out = TensorsConfig(info=TensorsInfo(new_infos), format=cfg.format,
                            rate=cfg.rate)
        return out.to_caps()

    def chain(self, pad, buf):
        spec = self._get_spec()
        idx = set(self._apply_indices(buf.num_tensors))
        out = [spec(t) if i in idx else t for i, t in enumerate(buf.tensors)]
        return self.srcpad.push(buf.with_tensors(out))

    # -- region fusion (pipeline/fuse.py) ------------------------------------
    def device_stage(self):
        """All transform modes are pure elementwise/layout math — always
        fusible when acceleration is on."""
        if not bool(self.get_property("acceleration")):
            return None
        from nnstreamer_tpu.pipeline.fuse import DeviceStage

        spec = self._get_spec()

        def fn(consts, tensors):
            import jax.numpy as jnp

            sel = set(self._apply_indices(len(tensors)))
            return [spec.apply(jnp, t) if i in sel else t
                    for i, t in enumerate(tensors)]

        key = ("tensor_transform", spec.mode, spec.option,
               str(self.get_property("apply") or ""))
        return DeviceStage(consts=None, fn=fn, key=key)
