"""tee — 1-to-N stream fan-out (gst core ``tee``).

Used throughout the reference's composite-model pipelines (one camera, N
models). Buffers are pushed to every src pad; payload arrays are shared
(buffers are immutable by convention), so fan-out of device arrays is free.
"""

from __future__ import annotations

from nnstreamer_tpu.pipeline.element import CapsEvent, Element, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin


@subplugin(ELEMENT, "tee")
class Tee(Element):
    ELEMENT_NAME = "tee"
    DEVICE_PASSTHROUGH = True  # pure fan-out: never reads tensor bytes

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")

    def request_src_pad(self):
        return self.add_src_pad(f"src_{len(self.srcpads)}")

    def link(self, downstream):
        # allocate a new src pad per link
        src = self.request_src_pad()
        sink = next((p for p in downstream.sinkpads if p.peer is None), None)
        if sink is None:
            sink = downstream.request_sink_pad()
        src.link(sink)
        # replay caps already seen
        if self.sinkpads[0].caps is not None:
            src.set_caps(self.sinkpads[0].caps)
        return downstream

    def chain(self, pad, buf):
        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META
        from nnstreamer_tpu.tensors.buffer import H2D_EXCLUSIVE_META

        if POOL_STASH_META in buf.meta or H2D_EXCLUSIVE_META in buf.meta:
            # fan-out would duplicate the staging-buffer release claim:
            # one branch's explicit release could recycle memory another
            # branch's in-flight device work still reads. Drop the claim
            # — the pool's GC fallback recycles once every branch is done.
            # The donation marker goes with it: a fanned-out payload has
            # N readers, so no branch's fused region may donate it.
            buf = buf.replace()
            buf.meta.pop(POOL_STASH_META, None)
            buf.meta.pop(H2D_EXCLUSIVE_META, None)
        ret = FlowReturn.OK
        for sp in self.srcpads:
            r = sp.push(buf)
            if r is FlowReturn.EOS:
                ret = r
        return ret
