"""tensor_sparse_enc / tensor_sparse_dec — dense↔sparse transcoding.

Reference: ``gst/nnstreamer/elements/gsttensorsparseenc.c`` (414 LoC) /
``...dec.c`` (408) + ``tensor_sparse/tensor_sparse_util.c``: COO-style
encoding (nnz values + flat indices) of mostly-zero tensors to save
transport bandwidth, emitted as flexible-format buffers with
self-describing headers.

Two selectable wire layouts (``layout`` property on the encoder; the
decoder sniffs the header and accepts both):

- ``reference`` (default): byte-exact ``GstTensorMetaInfo`` v1 header
  (128 B) + values[nnz] + uint32 flat indices[nnz] — the order
  gst_tensor_sparse_from_dense writes (tensor_sparse_util.c:236-240)
  — so streams interoperate with reference sparse_dec peers.
- ``native``: the framework's TMI1 header + uint32 indices[nnz] +
  values[nnz]; supports rank>4 and fp16/bf16 tensors the reference
  enum cannot express.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.meta import TensorMetaInfo, parse_header
from nnstreamer_tpu.tensors.types import (
    TensorFormat,
    TensorInfo,
    TensorsConfig,
)


def sparse_encode(arr: np.ndarray, layout: str = "reference") -> bytes:
    from nnstreamer_tpu import native

    if layout not in ("reference", "native"):
        raise ValueError(f"sparse_encode: unknown layout {layout!r} "
                         "(reference|native)")
    arr = np.ascontiguousarray(np.asarray(arr))
    idx, vals = native.sparse_encode_arrays(arr)  # GIL-free scan in C++
    meta = TensorMetaInfo.from_info(
        TensorInfo.from_array(arr), format=TensorFormat.SPARSE,
        sparse_nnz=int(idx.size),
    )
    if layout == "reference":
        # values first, then indices (tensor_sparse_util.c:236-240)
        return meta.pack_ref() + vals.tobytes() + idx.tobytes()
    return meta.pack() + idx.tobytes() + vals.tobytes()


def sparse_decode(blob: bytes, offset: int = 0):
    from nnstreamer_tpu import native
    from nnstreamer_tpu.tensors.meta import REF_HEADER_SIZE

    meta, hsize = parse_header(blob, offset)
    if meta.format is not TensorFormat.SPARSE:
        raise ValueError("sparse_decode: not a sparse payload")
    nnz = meta.sparse_nnz
    dtype = meta.type.np_dtype
    p = offset + hsize
    end = p + (dtype.itemsize + 4) * nnz
    if len(blob) < end:
        raise ValueError(f"sparse_decode: truncated payload ({len(blob)} "
                         f"bytes, header promises {end})")
    if hsize == REF_HEADER_SIZE:
        # reference order: values then flat indices
        vals = np.frombuffer(blob[p:p + dtype.itemsize * nnz], dtype)
        idx = np.frombuffer(blob[p + dtype.itemsize * nnz:end], np.uint32)
    else:
        idx = np.frombuffer(blob[p:p + 4 * nnz], np.uint32)
        vals = np.frombuffer(blob[p + 4 * nnz:end], dtype)
    info = meta.to_info()
    if nnz and int(idx.max()) >= info.num_elements:
        raise ValueError(f"sparse_decode: index {int(idx.max())} outside "
                         f"dense tensor of {info.num_elements} elements")
    dense = native.sparse_decode_arrays(idx, vals, info.num_elements)
    return dense.reshape(info.shape), end


@subplugin(ELEMENT, "tensor_sparse_enc")
class TensorSparseEnc(Element):
    ELEMENT_NAME = "tensor_sparse_enc"
    PROPERTIES = {
        **Element.PROPERTIES,
        "layout": "reference",
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return TensorsConfig(format=TensorFormat.SPARSE).to_caps()

    def chain(self, pad, buf):
        layout = self.get_property("layout")
        if layout not in ("reference", "native"):
            raise ValueError(f"tensor_sparse_enc: unknown layout {layout!r} "
                             "(reference|native)")
        host = buf.to_host()  # applies any deferred finalize exactly once
        blobs = [np.frombuffer(sparse_encode(t, layout=layout), np.uint8)
                 for t in host.tensors]
        return self.srcpad.push(host.with_tensors(blobs))


@subplugin(ELEMENT, "tensor_sparse_dec")
class TensorSparseDec(Element):
    ELEMENT_NAME = "tensor_sparse_dec"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return None  # static caps derive from the first decoded frame

    def chain(self, pad, buf):
        host = buf.to_host()
        outs = []
        for t in host.tensors:
            dense, _ = sparse_decode(np.ascontiguousarray(t).tobytes())
            outs.append(dense)
        if self.srcpad.caps is None:
            self.srcpad.set_caps(TensorsConfig.from_arrays(outs).to_caps())
        return self.srcpad.push(host.with_tensors(outs))
