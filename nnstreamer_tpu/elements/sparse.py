"""tensor_sparse_enc / tensor_sparse_dec — dense↔sparse transcoding.

Reference: ``gst/nnstreamer/elements/gsttensorsparseenc.c`` (414 LoC) /
``...dec.c`` (408) + ``tensor_sparse_util.c``: COO-style encoding (nnz
indices + values) of mostly-zero tensors to save transport bandwidth,
emitted as flexible-format buffers with self-describing headers.

Wire layout per tensor (after the TensorMetaInfo header, which carries the
dense dim/type and nnz): uint32 flat indices [nnz] then values [nnz].
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.meta import HEADER_SIZE, TensorMetaInfo
from nnstreamer_tpu.tensors.types import (
    TensorFormat,
    TensorInfo,
    TensorsConfig,
)


def sparse_encode(arr: np.ndarray) -> bytes:
    from nnstreamer_tpu import native

    arr = np.ascontiguousarray(np.asarray(arr))
    idx, vals = native.sparse_encode_arrays(arr)  # GIL-free scan in C++
    meta = TensorMetaInfo.from_info(
        TensorInfo.from_array(arr), format=TensorFormat.SPARSE,
        sparse_nnz=int(idx.size),
    )
    return meta.pack() + idx.tobytes() + vals.tobytes()


def sparse_decode(blob: bytes, offset: int = 0):
    meta = TensorMetaInfo.unpack(blob[offset:offset + HEADER_SIZE])
    if meta.format is not TensorFormat.SPARSE:
        raise ValueError("sparse_decode: not a sparse payload")
    from nnstreamer_tpu import native

    nnz = meta.sparse_nnz
    dtype = meta.type.np_dtype
    p = offset + HEADER_SIZE
    idx = np.frombuffer(blob[p:p + 4 * nnz], np.uint32)
    p += 4 * nnz
    vals = np.frombuffer(blob[p:p + dtype.itemsize * nnz], dtype)
    p += dtype.itemsize * nnz
    info = meta.to_info()
    dense = native.sparse_decode_arrays(idx, vals, info.num_elements)
    return dense.reshape(info.shape), p


@subplugin(ELEMENT, "tensor_sparse_enc")
class TensorSparseEnc(Element):
    ELEMENT_NAME = "tensor_sparse_enc"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return TensorsConfig(format=TensorFormat.SPARSE).to_caps()

    def chain(self, pad, buf):
        host = buf.to_host()  # applies any deferred finalize exactly once
        blobs = [np.frombuffer(sparse_encode(t), np.uint8)
                 for t in host.tensors]
        return self.srcpad.push(host.with_tensors(blobs))


@subplugin(ELEMENT, "tensor_sparse_dec")
class TensorSparseDec(Element):
    ELEMENT_NAME = "tensor_sparse_dec"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return None  # static caps derive from the first decoded frame

    def chain(self, pad, buf):
        host = buf.to_host()
        outs = []
        for t in host.tensors:
            dense, _ = sparse_decode(np.ascontiguousarray(t).tobytes())
            outs.append(dense)
        if self.srcpad.caps is None:
            self.srcpad.set_caps(TensorsConfig.from_arrays(outs).to_caps())
        return self.srcpad.push(host.with_tensors(outs))
