"""tensor_repo — named global slots enabling cycles (RNN/LSTM recurrence).

Reference: ``gst/nnstreamer/tensor_repo/`` — ``GstTensorRepo`` (hash of
slots with GCond push/pull, tensor_repo.h:36-60) + ``tensor_reposink`` /
``tensor_reposrc`` elements: a DAG-only pipeline gains feedback loops by
writing each frame's state to a slot and reading it back at the top of the
next iteration (tests/nnstreamer_repo_rnn).

TPU design: slot payloads may be device ``jax.Array``s — recurrent state
(e.g. LSTM hidden) stays in HBM across iterations with zero host
round-trips (SURVEY §5 checkpoint/resume analog). Slots can also be
snapshotted/restored for stateful-stream checkpointing.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from nnstreamer_tpu.pipeline.element import Element, FlowError, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


class TensorRepo:
    """Process-global named slots with blocking get (GCond semantics)."""

    def __init__(self):
        self._slots: Dict[str, Any] = {}
        self._cv = threading.Condition()

    def set(self, slot: str, buf: TensorBuffer) -> None:
        with self._cv:
            self._slots[slot] = buf
            self._cv.notify_all()

    def get(self, slot: str, timeout: Optional[float] = None,
            consume: bool = False) -> Optional[TensorBuffer]:
        with self._cv:
            if timeout is not None:
                import time

                deadline = time.monotonic() + timeout
                while slot not in self._slots:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cv.wait(timeout=left):
                        return None
            buf = self._slots.get(slot)
            if consume and slot in self._slots:
                del self._slots[slot]
            return buf

    def peek(self, slot: str) -> Optional[TensorBuffer]:
        with self._cv:
            return self._slots.get(slot)

    def remove(self, slot: str) -> bool:
        with self._cv:
            return self._slots.pop(slot, None) is not None

    def snapshot(self) -> Dict[str, list]:
        """Host-side snapshot of all slots (checkpoint of stream state)."""
        with self._cv:
            return {
                k: [np.asarray(t) for t in v.tensors]
                for k, v in self._slots.items()
            }

    def restore(self, state: Dict[str, list]) -> None:
        with self._cv:
            for k, arrays in state.items():
                self._slots[k] = TensorBuffer(list(arrays))
            self._cv.notify_all()


#: the process-global repo (reference: one static GstTensorRepo)
GLOBAL_REPO = TensorRepo()


@subplugin(ELEMENT, "tensor_reposink")
class TensorRepoSink(Element):
    """Writes each buffer into a repo slot (reference tensor_reposink.c)."""

    ELEMENT_NAME = "tensor_reposink"
    PROPERTIES = {**Element.PROPERTIES, "slot_index": 0, "slot": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")

    def _slot(self) -> str:
        return str(self.get_property("slot") or
                   self.get_property("slot_index"))

    def chain(self, pad, buf):
        GLOBAL_REPO.set(self._slot(), buf)
        return FlowReturn.OK


@subplugin(ELEMENT, "tensor_reposrc")
class TensorRepoSrc(SourceElement):
    """Reads a repo slot each iteration (reference tensor_reposrc.c).

    ``initial-dim``/``initial-type``/``initial-value`` provide the frame
    pushed before the loop produces its first state (the reference reads a
    caps-sized zero frame)."""

    ELEMENT_NAME = "tensor_reposrc"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "slot_index": 0,
        "slot": None,
        "num_buffers": -1,
        "initial_dim": None,
        "initial_type": "float32",
        "initial_value": 0.0,
        "timeout": 10.0,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def _slot(self) -> str:
        return str(self.get_property("slot") or
                   self.get_property("slot_index"))

    def negotiate(self):
        dim = self.get_property("initial_dim")
        if dim:
            from nnstreamer_tpu.tensors.types import (
                TensorsConfig,
                TensorsInfo,
            )

            info = TensorsInfo.from_str(str(dim),
                                        str(self.get_property("initial_type")))
            self.srcpad.set_caps(TensorsConfig(info=info).to_caps())

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        if self.i == 0 and self.get_property("initial_dim"):
            from nnstreamer_tpu.tensors.types import TensorInfo

            info = TensorInfo.from_str(
                str(self.get_property("initial_dim")),
                str(self.get_property("initial_type")),
            )
            arr = np.full(info.shape, float(self.get_property("initial_value")),
                          info.type.np_dtype)
            self.i += 1
            return TensorBuffer([arr], pts=0)
        t = float(self.get_property("timeout"))
        buf = GLOBAL_REPO.get(self._slot(), timeout=t, consume=True)
        if buf is None:
            # (the guard at the top already returned for i >= n)
            if n >= 0 and not self._stop_evt.is_set():
                # the pipeline promised n iterations and the loop state
                # vanished mid-count: that is a WEDGED loop (producer
                # died / reposink unlinked), not a drain — fail loudly
                # so failure detection sees it instead of a clean EOS.
                # A deliberate stop() mid-wait is NOT a wedge.
                raise FlowError(
                    f"tensor_reposrc: slot {self._slot()!r} starved "
                    f"after {self.i}/{n} iterations (timeout {t}s) — "
                    "repo loop wedged")
            return None  # endless loop drained / pipeline stopping → EOS
        self.i += 1
        return buf.replace(pts=self.i)
