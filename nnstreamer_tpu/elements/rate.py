"""tensor_rate — framerate adjustment + QoS throttling.

Reference: ``gst/nnstreamer/elements/gsttensorrate.c`` (997 LoC): converts
stream framerate by dropping/duplicating frames and, with ``throttle=true``,
propagates QoS so upstream inference skips work for frames that would be
dropped (gsttensorrate.c:27-36). Here the QoS rides a :class:`QosEvent`
upstream (posted at caps time and whenever the target rate changes);
``tensor_filter`` honors it in its invoke drop check.

``silent`` (reference gsttensorrate "silent" property) gates per-drop /
per-duplicate debug logging; counters are always kept.
"""

from __future__ import annotations

from typing import Optional

from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.pipeline.element import Element, QosEvent
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.types import Fraction, TensorsConfig


@subplugin(ELEMENT, "tensor_rate")
class TensorRate(Element):
    ELEMENT_NAME = "tensor_rate"
    DEVICE_PASSTHROUGH = True  # drops/duplicates whole buffers only
    PROPERTIES = {**Element.PROPERTIES, "framerate": None, "throttle": True,
                  "silent_drop": None}  # deprecated alias of `silent`

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._in_rate: Optional[Fraction] = None
        self._next_ts: Optional[float] = None  # set from first buffer's pts
        self._posted_interval: Optional[int] = None
        self.dropped = 0
        self.duplicated = 0
        self.out_count = 0
        self._m_dropped = None     # registry counters, created lazily so
        self._m_duplicated = None  # labels carry the owning pipeline name

    def _obs_counters(self):
        if self._m_dropped is None:
            reg = get_registry()
            labels = self._obs_labels()
            self._m_dropped = reg.counter(
                "nns_tensor_rate_dropped_total",
                "Frames dropped by framerate conversion", **labels)
            self._m_duplicated = reg.counter(
                "nns_tensor_rate_duplicated_total",
                "Frames duplicated by framerate conversion", **labels)
        return self._m_dropped, self._m_duplicated

    def obs_snapshot(self):
        out = super().obs_snapshot()
        out["drops"] = self.dropped
        out["duplicates"] = self.duplicated
        return out

    def _out_rate(self) -> Optional[Fraction]:
        spec = self.get_property("framerate")
        return Fraction.parse(spec) if spec else None

    def _post_qos(self) -> None:
        """Tell upstream the target inter-frame interval (0 lifts it)."""
        out = self._out_rate()
        interval = 0
        if bool(self.get_property("throttle")) and out is not None \
                and out.num > 0:
            interval = out.frame_duration_ns or 0
        # initial None counts as 0: a rate with no throttle to announce
        # must stay silent, not post a lift that cancels an upstream
        # rate's throttle mid-negotiation
        if interval != (self._posted_interval or 0):
            self.sinkpads[0].push_upstream_event(
                QosEvent(target_interval_ns=interval))
        self._posted_interval = interval

    def property_changed(self, key):
        if key == "silent_drop":  # deprecated alias, kept for old strings
            v = self.get_property("silent_drop")
            if v is not None:  # launch strings deliver str, API bool
                self.set_property("silent", str(v).strip().lower()
                                  in ("1", "true", "yes", "on"))
            return
        # guard: set_property runs from __init__ before our fields exist
        if key in ("framerate", "throttle") and \
                getattr(self, "_posted_interval", None) is not None:
            self._post_qos()

    def transform_caps(self, pad, caps):
        try:
            cfg = TensorsConfig.from_caps(caps)
            self._in_rate = cfg.rate
            out = self._out_rate()
            self._post_qos()
            if out is not None:
                cfg.rate = out
                return cfg.to_caps()
        except ValueError:
            pass
        return caps

    def chain(self, pad, buf):
        out_rate = self._out_rate()
        if out_rate is None or out_rate.num <= 0 or buf.pts is None:
            return self.srcpad.push(buf)
        period_ns = 1e9 * out_rate.den / out_rate.num
        if self._next_ts is None:
            self._next_ts = float(buf.pts)  # clock starts at the stream's
            # first timestamp (streams may carry wall-clock pts)
        ret = None
        pushed = False
        m_drop, m_dup = self._obs_counters()
        # emit one output per elapsed output period; duplicate if input is
        # slower, drop if faster
        while buf.pts >= self._next_ts:
            out = buf.replace(pts=int(self._next_ts),
                              duration=int(period_ns))
            ret = self.srcpad.push(out)
            self._next_ts += period_ns
            self.out_count += 1
            if pushed:
                self.duplicated += 1
                m_dup.inc()
                if not self.get_property("silent"):
                    self.log.debug("duplicated frame at pts %d", out.pts)
            pushed = True
        if not pushed:
            self.dropped += 1
            m_drop.inc()
            if not self.get_property("silent"):
                self.log.debug("dropped frame at pts %d (total %d)",
                               buf.pts, self.dropped)
        return ret
