"""tensor_split — one tensor → N tensors by size spec along a dimension.

Reference: ``gst/nnstreamer/elements/gsttensorsplit.c`` (706 LoC):
``tensorseg`` gives per-output sizes along ``dimension`` (innermost-first
index), e.g. ``tensorseg=1:100,1:100,1:56 dimension=1``.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nnstreamer_tpu.pipeline.element import CapsEvent, Element, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import is_device_array


@subplugin(ELEMENT, "tensor_split")
class TensorSplit(Element):
    ELEMENT_NAME = "tensor_split"
    DEVICE_PASSTHROUGH = True  # slicing stays lazy on device arrays
    PROPERTIES = {**Element.PROPERTIES, "tensorseg": None, "dimension": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._sizes: Optional[List[int]] = None

    def _get_sizes(self) -> List[int]:
        if self._sizes is None:
            spec = self.get_property("tensorseg")
            if spec is None:
                raise ValueError("tensor_split: tensorseg not set")
            # accept "100,100,56" or reference-style "1:100,1:100" (use the
            # split-dim component)
            dim_idx = int(self.get_property("dimension"))
            sizes = []
            for seg in str(spec).split(","):
                parts = [int(p) for p in seg.split(":")]
                sizes.append(parts[dim_idx] if len(parts) > dim_idx
                             else parts[-1] if len(parts) > 1 else parts[0])
            self._sizes = sizes
        return self._sizes

    def _ensure_pads(self, n: int):
        while len(self.srcpads) < n:
            self.add_src_pad(f"src_{len(self.srcpads)}")

    def request_src_pad(self):
        return self.add_src_pad(f"src_{len(self.srcpads)}")

    def link(self, downstream):
        # src pads are request-style: allocate one per link if all are taken
        if all(p.peer is not None for p in self.srcpads):
            self.request_src_pad()
        return super().link(downstream)

    def chain(self, pad, buf):
        sizes = self._get_sizes()
        self._ensure_pads(len(sizes))
        arr = buf.tensors[0]
        dim_idx = int(self.get_property("dimension"))
        axis = arr.ndim - 1 - dim_idx
        # plain ints: offsets come from the element's own sizes property,
        # never from a device array, and slice() takes them directly
        offsets = np.cumsum([0] + sizes).tolist()
        if offsets[-1] != arr.shape[axis]:
            raise ValueError(
                f"tensor_split: tensorseg sums to {offsets[-1]} but dim "
                f"{dim_idx} is {arr.shape[axis]}"
            )
        ret = FlowReturn.OK
        for i, sp in enumerate(self.srcpads[:len(sizes)]):
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(offsets[i], offsets[i + 1])
            part = arr[tuple(sl)]
            if sp.caps is None:
                from nnstreamer_tpu.tensors.types import TensorsConfig

                sp.set_caps(TensorsConfig.from_arrays([part]).to_caps())
            r = sp.push(buf.with_tensors([part]))
            if r is FlowReturn.EOS:
                ret = r
        return ret

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            return
        super().sink_event(pad, event)
