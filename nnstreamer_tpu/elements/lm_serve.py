"""tensor_lm_serve — distributed LM serving over the query transport.

Drops the continuous-batching engine (serving/engine.py) into the query
server topology the reference uses for offload
(/root/reference/gst/nnstreamer/tensor_query/tensor_query_server.c):

    tensor_query_serversrc ! tensor_lm_serve engine=E ! tensor_query_serversink

Each arriving buffer is a prompt (int32 ids, flattened); the element
submits it to the shared engine and returns ONE completion buffer (the
generated ids) when the stream finishes. Unlike the 1-buffer-at-a-time
filter the reference server runs, submission is asynchronous: every
in-flight request across ALL clients decodes in the same batched device
program, and completions flow downstream as they finish —

- ACROSS clients: out of order (serversink routes by ``query_client_id``
  meta, so a short prompt never waits on a long one);
- WITHIN a client: strictly FIFO (the framed query protocol matches
  responses to requests by order, so a per-client drainer pushes that
  client's completions in submission order).

Per-request overrides: a SECOND int32 tensor in the request buffer caps
generation for that prompt (the framed wire protocol carries tensors,
not meta, so the budget travels as payload); in-process pipelines may
use ``lm_max_new`` buffer meta instead. The completion buffer carries
``lm_finish_reason`` and ``lm_prompt_len`` meta and preserves everything
else (client id included) — meta is visible to downstream SERVER-side
elements; the wire back to the client carries TWO tensors: the generated
ids (int32) and the model's per-token logprobs (float32).

Failure contract: the framed protocol matches responses to requests BY
ORDER, so every request gets exactly one response — a request that fails
(bad prompt, engine error, result timeout) returns a single ``-1``
token (ids are never negative) instead of desynchronizing or killing
the server. Per-client drainers retire after ``idle_timeout`` seconds
without traffic, so a long-running server doesn't accumulate one thread
per connection ever made (the query server mints a fresh client id per
TCP connection); a completion that races the idle window is handed to a
fresh drainer rather than dropped, so retirement never costs a response.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict

import numpy as np

from nnstreamer_tpu.pipeline.element import (
    Element,
    EosEvent,
    FlowError,
    FlowReturn,
)
from nnstreamer_tpu.registry import ELEMENT, subplugin


@subplugin(ELEMENT, "tensor_lm_serve")
class TensorLMServe(Element):
    ELEMENT_NAME = "tensor_lm_serve"
    PROPERTIES = {
        **Element.PROPERTIES,
        "engine": "",            # registered engine name (serving package)
        "max_new_tokens": 64,    # default generation budget per request
        "timeout": 600.0,        # seconds a drainer waits on one result
        "idle_timeout": 60.0,    # seconds before an idle drainer retires
        "speculate": 0,          # draft-then-verify lookahead (engine knob)
        "speculate_layers": 0,   # draft depth override (0 = engine default)
    }

    #: error response payload — exactly one buffer per request keeps the
    #: order-matched framed protocol in sync (see module docstring)
    ERROR_TOKEN = -1

    _EOS = object()

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._engine = None
        from nnstreamer_tpu.utils.stats import InvokeStats

        #: submit→completion wall time per request — surfaced as this
        #: element's ``latency``/``throughput`` properties (the base
        #: ``stats`` window only times the synchronous chain() hand-off,
        #: which for an async element is meaningless µs)
        self.request_stats = InvokeStats()
        self._fifos: Dict[int, _queue.Queue] = {}
        #: cid → stream the drainer is currently waiting on (for
        #: cancel-on-stop/EOS-timeout coverage of dequeued items)
        self._current: Dict[int, object] = {}
        self._drainers: Dict[int, threading.Thread] = {}
        self._state_lock = threading.Lock()
        self._push_lock = threading.Lock()  # serialize downstream pushes
        self._inflight = 0
        self._stopped = False  # set under _state_lock; _enqueue rejects
        self._idle = threading.Condition(self._state_lock)

    def _metrics_stats(self):
        return self.request_stats

    def start(self):
        super().start()
        with self._state_lock:
            self._stopped = False
        from nnstreamer_tpu.serving import get_engine

        name = self.get_property("engine")
        self._engine = get_engine(name)
        if self._engine is None:
            raise FlowError(
                f"{self.name}: no engine registered as {name!r} "
                f"(serving.register_engine first)")
        spec = int(self.get_property("speculate"))
        if spec and spec != getattr(self._engine, "speculate", 0):
            # opt-in draft-then-verify: the knob lives on the element so
            # a pipeline string can turn it on, but the machinery is the
            # engine's (models/speculative.py). set_speculate raises if
            # the engine is already mid-decode with a different K — a
            # config conflict that should fail start(), not be papered
            # over.
            layers = int(self.get_property("speculate_layers")) or None
            self._engine.set_speculate(spec, draft_layers=layers)

    def _cancel_all_inflight(self):
        """Nobody will read these streams anymore — the engine must not
        keep decoding into them (their slots free at the next block
        boundary)."""
        with self._state_lock:
            fifos = list(self._fifos.values())
            current = list(self._current.values())
        for st in current:
            if st is not None:
                st.cancel()
        for f in fifos:
            for item in list(f.queue):
                if isinstance(item, tuple) and item[0] is not None:
                    item[0].cancel()

    def stop(self):
        self._cancel_all_inflight()
        with self._state_lock:
            # chain() racing stop() must not recreate fifos/drainers after
            # this point — _enqueue pushes an error response instead
            self._stopped = True
            fifos = list(self._fifos.values())
            self._fifos.clear()
            drainers = list(self._drainers.values())
            self._drainers.clear()
            self._current.clear()
        for f in fifos:
            f.put(self._EOS)
        for t in drainers:
            t.join(timeout=5)
        self._engine = None
        super().stop()

    # -- request intake -------------------------------------------------------
    def chain(self, pad, buf):
        cid = int(buf.meta.get("query_client_id", 0))
        try:
            # query-wire payloads are host arrays by construction (the
            # protocol deserializes into numpy) — no device sync here
            prompt = np.asarray(  # nns-lint: disable=NNS107,NNS108 -- wire payload is host by construction
                buf.tensors[0]).reshape(-1).astype(np.int32)
            max_new = int(self.get_property("max_new_tokens"))
            if len(buf.tensors) > 1:  # budget as payload (survives wire)
                max_new = int(np.asarray(  # nns-lint: disable=NNS107,NNS108 -- wire
                    buf.tensors[1]).reshape(-1)[0])
            max_new = int(buf.meta.get("lm_max_new", max_new))
            stream = self._engine.submit(prompt, max_new_tokens=max_new)
            self._enqueue(cid, (stream, buf, None, time.monotonic()))
        except Exception as e:  # noqa: BLE001  # nns-lint: disable=NNS111 -- failure surfaces as an in-order error RESPONSE, not a bus error
            # a malformed remote
            # request must not error the server pipeline (remote DoS);
            # its error response goes through the SAME per-client fifo so
            # it cannot overtake earlier in-flight completions (the wire
            # matches responses to requests by order)
            self.log.warning("client %d request rejected: %s", cid, e)
            self._enqueue(cid, (None, buf, str(e), time.monotonic()))
        return FlowReturn.OK

    def _enqueue(self, cid: int, item) -> None:
        with self._state_lock:
            if self._stopped:
                rejected = item
            else:
                rejected = None
                fifo = self._fifos.get(cid)
                if fifo is None:
                    fifo = self._fifos[cid] = _queue.Queue()
                    t = threading.Thread(target=self._drain,
                                         args=(cid, fifo),
                                         name=f"{self.name}-c{cid}",
                                         daemon=True)
                    self._drainers[cid] = t
                    t.start()
                self._inflight += 1
                fifo.put(item)
        if rejected is not None:
            # element stopped between chain() and here: the client still
            # gets its error response, and no drainer is recreated
            stream, buf, _err, _t0 = rejected
            if stream is not None:
                stream.cancel()
            self._push_response(
                self._error_response(buf, "server stopped"))

    def _error_response(self, buf, reason: str):
        return buf.with_tensors(
            [np.asarray([self.ERROR_TOKEN], np.int32)]).replace(
                meta={**buf.meta, "lm_finish_reason": f"error: {reason}"})

    def _push_response(self, out):
        with self._push_lock:
            self.srcpad.push(out)

    def _adopt_orphans_locked(self, cid: int, items) -> None:
        """Hand completions orphaned by a retiring drainer to a fresh
        one. Caller holds ``_state_lock`` and has already removed the
        old fifo/drainer for ``cid``, so registering here is
        race-free; ``_inflight`` was counted at original enqueue and
        must NOT be bumped again. (``stop()`` clears the fifo map in
        the same critical section that sets ``_stopped``, so reaching
        this path implies the element is still running.)"""
        fifo = self._fifos[cid] = _queue.Queue()
        for item in items:
            fifo.put(item)
        t = threading.Thread(target=self._drain, args=(cid, fifo),
                             name=f"{self.name}-c{cid}", daemon=True)
        self._drainers[cid] = t
        t.start()

    # -- per-client completion drainer ---------------------------------------
    def _drain(self, cid: int, fifo: _queue.Queue):
        timeout = float(self.get_property("timeout"))
        idle = float(self.get_property("idle_timeout"))
        while True:
            try:
                item = fifo.get(timeout=idle)
            except _queue.Empty:
                # Retire — carefully. A completion can land in the fifo
                # between the idle timeout firing and the removal below
                # (the engine finishes a stream just as the window
                # closes). Dropping it would desync the framed
                # protocol's one-response-per-request contract; but a
                # retiring drainer must not keep consuming either, or a
                # new request for the same client would spawn a SECOND
                # drainer and the two would interleave responses. So:
                # unregister under the lock, then hand any orphaned
                # items to a fresh drainer that takes over the cid.
                with self._state_lock:
                    if self._fifos.get(cid) is not fifo:
                        # replaced or stopped: whoever owns the cid now
                        # (or stop()'s _EOS, already in OUR fifo) drains
                        # the rest — keep looping until we see it
                        continue
                    del self._fifos[cid]
                    del self._drainers[cid]
                    orphans = []
                    try:
                        while True:
                            orphans.append(fifo.get_nowait())
                    except _queue.Empty:
                        pass
                    if orphans:
                        self._adopt_orphans_locked(cid, orphans)
                return
            if item is self._EOS:
                return
            stream, buf, err, t0 = item
            with self._state_lock:
                self._current[cid] = stream
            try:
                if stream is None:  # rejected at intake, in FIFO order
                    self._push_response(self._error_response(buf, err))
                    continue
                toks = stream.result(timeout=timeout)
                reason = stream.finish_reason or ""
                if reason not in ("eos", "length"):
                    # engine-side failure (prefill/dispatch error, engine
                    # stopped): result() returns [] without raising — the
                    # client still gets the documented -1 error response
                    self._push_response(self._error_response(buf, reason))
                    continue
                # the serving analog of the filter's invoke window
                # (tensor_filter.c:325-423): one sample per SUCCESSFUL
                # request — failures must not floor the latency window
                self.request_stats.record(time.monotonic() - t0)
                out = buf.with_tensors(
                    # tokens + the model's per-token logprobs (second
                    # tensor — payload, so it crosses the wire like the
                    # request's budget tensor does)
                    [np.asarray(toks, np.int32),
                     np.asarray(stream.logprobs[:len(toks)],
                                np.float32)]).replace(meta={
                        **buf.meta,
                        "lm_finish_reason": reason,
                        "lm_prompt_len": stream.prompt_len,
                    })
                self._push_response(out)
            except Exception as e:  # noqa: BLE001  # nns-lint: disable=NNS111 -- failure surfaces as an in-order error RESPONSE, not a bus error
                # one failed request
                # must neither kill the drainer nor skip a response (the
                # order-matched protocol would attribute every later
                # completion to the wrong request)
                self.log.warning("client %d request failed: %s", cid, e)
                if stream is not None:
                    # e.g. result() timeout: the client already gets an
                    # error response, so stop the engine from decoding
                    # into the abandoned stream (its slot frees at the
                    # next block boundary); idempotent if already done
                    stream.cancel()
                try:
                    self._push_response(self._error_response(buf, str(e)))
                except Exception as e2:  # noqa: BLE001  # nns-lint: disable=NNS111 -- downstream gone: nothing left to post to
                    self.log.warning("client %d error response dropped: "
                                     "%s", cid, e2)
            finally:
                with self._idle:
                    self._current.pop(cid, None)
                    self._inflight -= 1
                    self._idle.notify_all()

    # -- EOS: drain everything first -----------------------------------------
    def sink_event(self, pad, event):
        if isinstance(event, EosEvent):
            with self._idle:
                done = self._idle.wait_for(
                    lambda: self._inflight == 0,
                    timeout=float(self.get_property("timeout")))
            if not done:
                # late completions will hit an eos'd pad and vanish —
                # stop the engine from decoding into them, and surface
                # WHY those clients never got a response
                self._cancel_all_inflight()
                self.post_error(FlowError(
                    f"{self.name}: EOS with requests still in flight "
                    f"after {self.get_property('timeout')}s; remaining "
                    f"completions will be dropped"))
            super().sink_event(pad, event)
            return
        super().sink_event(pad, event)
