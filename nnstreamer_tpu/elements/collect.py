"""CollectPads + timestamp-sync policies for N-to-1 elements.

Reference: ``gst/nnstreamer/tensor_common_pipeline.c`` (707 LoC) — the four
pad-sync policies shared by tensor_mux/tensor_merge
(``tensor_time_sync_mode``, tensor_common.h:62-69;
Documentation/synchronization-policies-at-mux-merge.md):

- ``nosync``  — combine in arrival order; one output per full set.
- ``slowest`` — sync to the slowest pad: output timestamp is the max of the
  collected pts; every pad contributes its buffer closest to that time.
- ``basepad`` — sync to a chosen pad (option ``<pad>:<duration>``): output
  per base-pad buffer, others contribute their latest buffer within the
  duration window (stale ones are reused).
- ``refresh`` — output whenever ANY pad receives a buffer, reusing the
  last-known buffer of the other pads.

Mechanics: producer threads call :meth:`push`; the policy decides when a
full frame-set is ready and which buffers compose it. All control flow is
host-side; payloads (possibly device arrays) are only routed, never copied
— the handle-based design SURVEY §7 calls for.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from nnstreamer_tpu.tensors.buffer import TensorBuffer

SYNC_POLICIES = ("nosync", "slowest", "basepad", "refresh")

#: buffer-meta key carrying the CollectPads arrival stamp (popped when
#: the buffer leaves in a frame-set, so it never travels downstream)
_ARRIVE_KEY = "_collect_arrive_t"


class CollectPads:
    """Collects one buffer per pad according to a sync policy and emits
    combined frame-sets via ``on_ready([(pad_index, buffer), ...])``.

    ``observe_wait`` (optional) receives, per emitted frame-set, the
    sync-wait in seconds: how long the set's EARLIEST-arriving buffer
    sat waiting for its peers — the pipeline-visible cost of the sync
    policy (a slow pad shows up here before it shows up as fps loss).
    """

    def __init__(self, num_pads: int, policy: str = "slowest",
                 option: str = "",
                 on_ready: Optional[Callable[[List[tuple]], None]] = None,
                 observe_wait: Optional[Callable[[float], None]] = None):
        if policy not in SYNC_POLICIES:
            raise ValueError(f"unknown sync policy {policy!r}")
        self.num_pads = num_pads
        self.policy = policy
        self.on_ready = on_ready
        self.observe_wait = observe_wait
        self._lock = threading.Lock()
        self._queues: Dict[int, List[TensorBuffer]] = {
            i: [] for i in range(num_pads)
        }
        self._last: Dict[int, Optional[TensorBuffer]] = {
            i: None for i in range(num_pads)
        }
        self._eos: Dict[int, bool] = {i: False for i in range(num_pads)}
        self.base_pad = 0
        self.base_window_ns = 0
        if policy == "basepad" and option:
            parts = str(option).split(":")
            self.base_pad = int(parts[0])
            if len(parts) > 1:
                self.base_window_ns = int(parts[1])

    def add_pad(self) -> int:
        with self._lock:
            i = self.num_pads
            self.num_pads += 1
            self._queues[i] = []
            self._last[i] = None
            self._eos[i] = False
            return i

    # -- input ---------------------------------------------------------------
    def push(self, pad_index: int, buf: TensorBuffer) -> None:
        ready = None
        if self.observe_wait is not None:
            buf.meta[_ARRIVE_KEY] = time.monotonic()
        with self._lock:
            self._queues[pad_index].append(buf)
            self._last[pad_index] = buf
            ready = self._collect_locked(pad_index)
        if ready and self.on_ready:
            for frame in ready:
                self._observe_frame(frame)
                self.on_ready(frame)

    def _observe_frame(self, frame: List[tuple]) -> None:
        """Report the frame-set's sync wait (earliest arrival → now).
        Stamps are popped so a buffer reused by the ``refresh`` policy
        contributes its wait only once."""
        if self.observe_wait is None:
            return
        stamps = [b.meta.pop(_ARRIVE_KEY, None) for _, b in frame]
        stamps = [t for t in stamps if t is not None]
        if stamps:
            self.observe_wait(time.monotonic() - min(stamps))

    def requeue_front(self, pad_index: int, buf: TensorBuffer) -> None:
        """Put a buffer back at the head of a pad's queue (no collect
        trigger) — used by consumers that reject a pairing and keep the
        newer buffer for the next one (tensor_crop lateness). Follow with
        :meth:`recheck` once the rejection is fully handled."""
        with self._lock:
            self._queues[pad_index].insert(0, buf)

    def recheck(self) -> List[List[tuple]]:
        """Re-run collection without a new arrival (after requeue_front or
        EOS) and dispatch any now-ready frames. Not for the ``refresh``
        policy, which is strictly arrival-driven."""
        if self.policy == "refresh":
            raise ValueError("recheck() is undefined for policy 'refresh'")
        with self._lock:
            ready = self._collect_locked(-1)
        if ready and self.on_ready:
            for frame in ready:
                self._observe_frame(frame)
                self.on_ready(frame)
        return ready

    def set_eos(self, pad_index: int) -> bool:
        """Mark a pad EOS; returns True when ALL pads are EOS."""
        with self._lock:
            self._eos[pad_index] = True
            return all(self._eos.values())

    # -- policies ------------------------------------------------------------
    def _collect_locked(self, arrived: int) -> List[List[tuple]]:
        frames = []
        if self.policy in ("nosync", "slowest"):
            # both need a full set; slowest additionally aligns timestamps
            while all(q or self._eos[i]
                      for i, q in self._queues.items()) and any(
                          q for q in self._queues.values()):
                if not all(self._queues[i] for i in self._queues
                           if not self._eos[i]):
                    break
                live = [i for i in self._queues if self._queues[i]]
                if len(live) < sum(1 for i in self._eos if not self._eos[i]):
                    break
                if self.policy == "slowest" and len(live) > 1:
                    # drop buffers older than the slowest head timestamp
                    base = max(
                        (self._queues[i][0].pts or 0) for i in live
                    )
                    for i in live:
                        q = self._queues[i]
                        while len(q) > 1 and (q[1].pts or 0) <= base:
                            q.pop(0)
                frames.append([(i, self._queues[i].pop(0)) for i in live])
        elif self.policy == "basepad":
            while self._queues[self.base_pad]:
                base_buf = self._queues[self.base_pad][0]
                others_ready = True
                for i in self._queues:
                    if i == self.base_pad or self._eos[i]:
                        continue
                    if not self._queues[i] and self._last[i] is None:
                        others_ready = False
                        break
                if not others_ready:
                    break
                self._queues[self.base_pad].pop(0)
                frame = [(self.base_pad, base_buf)]
                base_ts = base_buf.pts or 0
                for i in self._queues:
                    if i == self.base_pad:
                        continue
                    q = self._queues[i]
                    # advance to the newest buffer not beyond the window
                    chosen = self._last[i]
                    while q:
                        cand = q[0]
                        if self.base_window_ns and cand.pts is not None and \
                                cand.pts > base_ts + self.base_window_ns:
                            break
                        chosen = q.pop(0)
                    if chosen is not None:
                        frame.append((i, chosen))
                frames.append(sorted(frame))
        elif self.policy == "refresh":
            if all(self._last[i] is not None or self._eos[i]
                   for i in self._queues):
                frame = [(i, self._last[i]) for i in self._queues
                         if self._last[i] is not None]
                self._queues[arrived].clear()
                frames.append(frame)
        return frames

    def flush_remaining(self) -> List[List[tuple]]:
        """At EOS: emit any complete-as-possible leftover sets (nosync)."""
        with self._lock:
            frames = []
            while any(q for q in self._queues.values()):
                frame = [(i, q.pop(0)) for i, q in self._queues.items() if q]
                if self.policy in ("nosync",) and frame:
                    frames.append(frame)
                else:
                    break
            return frames
