"""Sink elements: application callback sink, file sink, fakesink.

Reference: ``tensor_sink`` (gst/nnstreamer/elements/gsttensorsink.c, 644 LoC)
emits a ``new-data`` GSignal per buffer to the app; gst core filesink/fakesink
are used throughout the reference's SSAT golden tests (dump + byte-compare).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline.element import Element, EosEvent, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


class LatencyReservoir:
    """Bounded latency sample population with exact percentiles up to
    ``cap`` and uniform reservoir sampling (Vitter's algorithm R) beyond.

    A ``deque(maxlen=N)`` is a *sliding window*: on a long run it
    silently discards the oldest samples and the reported p50/p99 drift
    toward recent traffic only. A reservoir keeps every sample equally
    likely to be in the population regardless of stream length, so the
    percentiles describe the WHOLE run at O(cap) memory — and below the
    cap the population is complete, so percentiles are exact. The RNG is
    seeded so repeated runs of a deterministic pipeline report identical
    stats."""

    __slots__ = ("cap", "count", "_vals", "_rng")

    def __init__(self, cap: int = 65_536, seed: int = 0x5EED):
        self.cap = int(cap)
        self.count = 0  # samples OFFERED (not retained) — honest stream n
        self._vals: List[float] = []
        self._rng = random.Random(seed)

    def append(self, v: float) -> None:
        self.count += 1
        if len(self._vals) < self.cap:
            self._vals.append(v)
            return
        j = self._rng.randrange(self.count)
        if j < self.cap:
            self._vals[j] = v

    def __len__(self) -> int:
        return len(self._vals)

    def __iter__(self):
        return iter(self._vals)

    def clear(self) -> None:
        self.count = 0
        self._vals.clear()


@subplugin(ELEMENT, "tensor_sink")
class TensorSink(Element):
    """Terminal sink exposing buffers to the application.

    ``connect(cb)`` mirrors the reference's ``new-data`` signal
    (gsttensorsink "new-data"); buffers are also collected (bounded by
    ``max_stored``) for pull-style access, and :meth:`wait` blocks until N
    buffers or EOS.
    """

    # keeps a pending finalize lazy until chain(): it is applied at this
    # element's materialization point rather than on pad entry, so upstream
    # queues can batch the D2H instead of each frame syncing eagerly
    HANDLES_DEFERRED = True
    #: the chain below owns its materialization point (the sanctioned
    #: to_host call) — entry must not force an extra copy first
    DEVICE_PASSTHROUGH = True

    ELEMENT_NAME = "tensor_sink"
    PROPERTIES = {**Element.PROPERTIES, "sync": False, "max_stored": 4096,
                  "to_host": True}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers: List[TensorBuffer] = []
        self._callbacks: List[Callable[[TensorBuffer], None]] = []
        self._cv = threading.Condition()
        self.eos = False
        #: end-to-end per-frame latencies in seconds (create_t → chain);
        #: ring-bounded so long-lived live pipelines don't grow forever
        self.latencies: deque = deque(maxlen=100_000)
        #: latencies of frames ADMITTED by an upstream stamp_admission
        #: queue (leaky ingress): the served-traffic population — under
        #: saturation `latencies` still includes pre-admission backlog
        #: wait, which measures the source's free-running pace, not the
        #: pipeline's service time. Reservoir-bounded (not a sliding
        #: window): long runs keep a uniform sample of the WHOLE stream,
        #: exact percentiles up to the cap.
        self.admitted_latencies = LatencyReservoir()
        self._m_e2e = None  # lazy: labels need the owning pipeline's name

    def _obs_e2e(self):
        if self._m_e2e is None:
            from nnstreamer_tpu.obs import get_registry

            self._m_e2e = get_registry().histogram(
                "nns_sink_e2e_seconds",
                "End-to-end frame latency, source create() to sink",
                **self._obs_labels())
        return self._m_e2e

    def obs_snapshot(self):
        out = super().obs_snapshot()
        pcts = self.latency_percentiles(50.0, 99.0)
        if pcts is not None:
            out["e2e_p50_ms"], out["e2e_p99_ms"] = pcts
        return out

    def connect(self, callback: Callable[[TensorBuffer], None]) -> None:
        """Register a per-buffer callback (reference ``new-data`` signal)."""
        self._callbacks.append(callback)

    def chain(self, pad, buf):
        # pooled host staging arrays riding in meta (queue prefetch-device
        # stamped them, no dispatch window claimed them): pop the claim
        # now — released below once materialization proves the device
        # work that read them is complete, else left to the GC fallback
        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META

        stash = buf.meta.pop(POOL_STASH_META, None)
        # a pending finalize is ALWAYS applied — even with to_host=false —
        # so the app sees the same payload/meta as in an unfused pipeline
        # (with to_host=false the materialization only fetches the deferred
        # stage's tensors, e.g. two scalars, never full frames)
        if self.get_property("to_host") or buf.finalize is not None:
            buf = buf.to_host()
            # a latency-budget partial window (aggregator
            # latency-budget-ms) was padded to the compiled batch shape;
            # trim each tensor back to its k valid leading rows so the
            # app never sees the padding frames
            k = buf.meta.get("valid_frames")
            if k:
                buf = buf.with_tensors([
                    t[:k] if getattr(t, "ndim", 0) and t.shape[0] > k
                    else t for t in buf.tensors])
        # sink-stage span starts AFTER materialization: the D2H block is
        # already recorded (inside to_host) as this frame's d2h stage
        tl = _timeline.ACTIVE
        t_sink0 = time.monotonic() if tl is not None else 0.0
        e2e_s: Optional[float] = None
        e2e_adm_s: Optional[float] = None
        # end-to-end frame latency: source create() → here (payload is
        # host-materialized above). Under micro-batching meta carries one
        # capture stamp per constituent frame, so each frame's latency
        # includes its batch-window wait (BASELINE.md north-star metric;
        # the reference self-measures around its hot path the same way,
        # tensor_filter.c:349-423).
        # only record once the payload is actually host-resident —
        # recording a device handle's arrival would measure dispatch
        # enqueue, not completion (the round-3 bench-honesty rule)
        if stash and not buf.on_device():
            # host-materialized output ⇒ the dispatch that consumed the
            # staging arrays is complete ⇒ safe to recycle them
            from nnstreamer_tpu.tensors.pool import get_pool

            get_pool().release_many(stash)
        if not buf.on_device():
            now = time.monotonic()
            stamps = buf.create_stamps()
            if stamps:
                hist = self._obs_e2e()
                for t in stamps:
                    self.latencies.append(now - t)
                    hist.observe(now - t)
                if tl is not None:
                    # the frame's measured e2e rides on the sink span —
                    # the reconciliation denominator for stage_breakdown
                    e2e_s = now - (sum(stamps) / len(stamps))
            # aggregated buffers carry one admission stamp per
            # constituent frame (meta["admitted_ts"], kept in lockstep
            # with create_ts by tensor_aggregator); unaggregated ones
            # the single stamp the queue wrote
            adm_list = buf.meta.get("admitted_ts")
            if adm_list is None:
                adm = buf.meta.get("admitted_t")
                if adm is not None:
                    # one stamp covers the buffer; count it once per
                    # constituent frame so the served population weighs
                    # frames like `latencies` does
                    adm_list = [adm] * max(len(stamps), 1)
            if adm_list:
                frames = len(adm_list)
                for t in adm_list:
                    self.admitted_latencies.append(now - t)
                adm = adm_list[0]
                if tl is not None:
                    # admitted e2e rides alongside: the SLO burn windows
                    # judge deadline breaches from admission, not capture
                    e2e_adm_s = now - adm
                sched = getattr(self.pipeline, "_slo_scheduler", None)
                if sched is not None:
                    # completion feed: drives the drain-rate estimate
                    # (covers fused pipelines where the filter chain
                    # never runs) and the feedback controller's p99 —
                    # event-driven, the controller has no polling thread
                    sched.observe_completion(now - adm, now,
                                             frames=frames)
        with self._cv:
            if len(self.buffers) < int(self.get_property("max_stored")):
                self.buffers.append(buf)
            self._cv.notify_all()
        for cb in self._callbacks:
            cb(buf)
        if tl is not None:
            seq = buf.meta.get(_timeline.TRACE_SEQ_META)
            if seq is not None:
                if e2e_s is not None:
                    extra = {"e2e_adm_s": e2e_adm_s} \
                        if e2e_adm_s is not None else {}
                    tl.span("sink", seq, t_sink0, time.monotonic(),
                            track=self.name, e2e_s=e2e_s, **extra)
                else:
                    tl.span("sink", seq, t_sink0, time.monotonic(),
                            track=self.name)
        return FlowReturn.OK

    def latency_percentiles(self, *qs: float, skip: int = 0,
                            base: str = "create"):
        """End-to-end frame latency percentiles in ms, the queryable
        pipeline stat counterpart of the per-element InvokeStats.
        ``base="create"`` measures from the source capture stamp;
        ``base="admitted"`` from the upstream stamp_admission queue's
        accept point (served-traffic latency — None when no queue
        stamps). Default (p50, p99). ``skip`` drops the first N frames
        (warm-up exclusion for paced measurements; meaningful for the
        chronological ``create`` population — the admitted population is
        reservoir-sampled past its cap, where positional skipping no
        longer maps to stream order)."""
        pop = self.admitted_latencies if base == "admitted" else self.latencies
        vals = list(pop)[skip:]
        if not vals:
            return None
        qs = qs or (50.0, 99.0)
        vals = np.asarray(vals, dtype=np.float64) * 1e3
        return tuple(float(np.percentile(vals, q)) for q in qs)

    def sink_event(self, pad, event):
        if isinstance(event, EosEvent):
            with self._cv:
                self.eos = True
                self._cv.notify_all()
        super().sink_event(pad, event)

    def wait(self, n: int = 1, timeout: float = 30.0) -> List[TensorBuffer]:
        """Block until at least ``n`` buffers arrived or EOS/timeout."""
        import time

        deadline = time.monotonic() + timeout
        with self._cv:
            while len(self.buffers) < n and not self.eos:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    break
            return list(self.buffers)


@subplugin(ELEMENT, "filesink")
class FileSink(Element):
    """Dump raw tensor bytes to a file (gst filesink) — the SSAT
    golden-output pattern: run pipeline, byte-compare the dump."""

    ELEMENT_NAME = "filesink"
    DEVICE_PASSTHROUGH = True  # chain's own to_host is the fetch point
    PROPERTIES = {**Element.PROPERTIES, "location": None, "append": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._fh = None

    def start(self):
        super().start()
        loc = self.get_property("location")
        if loc is None:
            raise ValueError("filesink: location not set")
        mode = "ab" if self.get_property("append") else "wb"
        self._fh = open(loc, mode)

    def chain(self, pad, buf):
        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META

        stash = buf.meta.pop(POOL_STASH_META, None)
        buf = buf.to_host()
        if stash:
            from nnstreamer_tpu.tensors.pool import get_pool

            get_pool().release_many(stash)
        for t in buf.tensors:
            self._fh.write(np.ascontiguousarray(t).tobytes())
        return FlowReturn.OK

    def handle_eos(self):
        if self._fh:
            self._fh.flush()

    def stop(self):
        if self._fh:
            self._fh.close()
            self._fh = None
        super().stop()


@subplugin(ELEMENT, "fakesink")
class FakeSink(Element):
    """Discard buffers (gst fakesink); counts them for tests."""

    HANDLES_DEFERRED = True  # discards buffers; never forces the D2H
    DEVICE_PASSTHROUGH = True  # ditto for resident payloads

    ELEMENT_NAME = "fakesink"
    PROPERTIES = {**Element.PROPERTIES, "sync": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.count = 0

    def chain(self, pad, buf):
        self.count += 1
        return FlowReturn.OK
