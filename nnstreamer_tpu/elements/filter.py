"""tensor_filter — THE inference element.

Reference: ``gst/nnstreamer/elements/gsttensorfilter.c`` (1297 LoC) +
``tensor_filter_common.c`` (3001 LoC). Wraps a FilterFramework backend;
negotiates caps from the model's tensor info; per-frame it maps inputs,
invokes the backend (the HOT LOOP, tensor_filter.c:547-785), records
latency/throughput statistics (:325-423), and supports:

- ``framework=auto`` — detect backend from model extension by configured
  priority (tensor_filter_common.c:1200);
- ``input-combination``/``output-combination`` — route a subset of input
  tensors to the model and merge model outputs with passthrough inputs
  (tensor_filter_common.c combination props);
- ``shared-tensor-filter-key`` — cross-instance model sharing;
- ``is-updatable`` + ``reload_model`` custom event — hot model reload
  (RELOAD_MODEL, nnstreamer_plugin_api_filter.h:377-383);
- ``throttle`` QoS — drop frames when downstream lags (tensor_filter.c:426).

TPU specifics: backends with ``KEEP_ON_DEVICE`` receive whatever arrived
(host or device array) and return device arrays — a chain of
converter→transform→filter→decoder keeps payloads in HBM end to end; XLA's
async dispatch means invoke() returns before the device finishes, so
pipeline stages overlap naturally.
"""

from __future__ import annotations

import os
import time as _time
from typing import Any, List, Optional

import numpy as np

from nnstreamer_tpu.config import get_conf
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.filters.api import FilterFramework, FilterProperties
from nnstreamer_tpu.pipeline.element import (
    CustomEvent,
    Element,
    Event,
    Pad,
    peer_device_capable,
)
from nnstreamer_tpu.registry import ELEMENT, FILTER, get_subplugin, subplugin
from nnstreamer_tpu.tensors.buffer import DeviceBuffer, as_device_buffer
from nnstreamer_tpu.tensors.types import (
    TensorsConfig,
    TensorsInfo,
)


def detect_framework(model: str) -> Optional[str]:
    """framework=auto: first loadable backend for this model's extension
    (reference gst_tensor_filter_detect_framework,
    tensor_filter_common.c:1200)."""
    for cand in get_conf().framework_priority(model):
        if get_subplugin(FILTER, cand) is not None:
            return cand
    return None


def _parse_combination(spec: Optional[str]) -> Optional[List[tuple]]:
    """Parse "i0,i2" / "o0,i1" into [(kind, idx), ...]."""
    if not spec:
        return None
    out = []
    for item in str(spec).split(","):
        item = item.strip().lower()
        if not item:
            continue
        kind, idx = item[0], item[1:]
        if kind not in ("i", "o") or not idx.isdigit():
            raise ValueError(f"bad combination item {item!r}")
        out.append((kind, int(idx)))
    return out


@subplugin(ELEMENT, "tensor_filter")
class TensorFilter(Element):
    ELEMENT_NAME = "tensor_filter"
    #: device backends consume jax.Arrays as-is; for host-only backends
    #: chain() below materializes via the sanctioned cached to_host
    DEVICE_PASSTHROUGH = True
    PROPERTIES = {
        **Element.PROPERTIES,
        "framework": "auto",
        "model": None,
        "custom": None,
        "accelerator": None,
        "input": None,            # forced input dims "3:224:224:1"
        "inputtype": None,
        "output": None,
        "outputtype": None,
        "is_updatable": False,
        "input_combination": None,
        "output_combination": None,
        "shared_tensor_filter_key": None,
        # multi-chip serving plane (parallel/serve.py): mesh spec like
        # "dp4" / "dp2xtp2" / "dp-1" — batch-shards the invoke over the
        # device mesh with replicated weights; "shard" is an accepted
        # alias. Unset (or NNSTPU_MESH=0) = byte-identical single-device
        # path.
        "mesh": None,
        "shard": None,
        "throttle": 0,            # max invokes/sec; 0 = unthrottled
        # max device batches outstanding past this filter before the
        # producer thread fences the oldest (pipeline/dispatch.py):
        # 2 overlaps host work for frame N+1 with device compute of
        # frame N; 0 fences every frame (fully synchronous)
        "inflight": 2,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        from nnstreamer_tpu.pipeline.dispatch import DispatchWindow

        self._window = DispatchWindow(self)
        self.fw: Optional[FilterFramework] = None
        self._in_model_info: Optional[TensorsInfo] = None
        self._in_full_info: Optional[TensorsInfo] = None
        self._out_model_info: Optional[TensorsInfo] = None
        self._last_invoke_t = 0.0
        self._comb_cache: dict = {}
        self._m_invoke = None  # created lazily: labels need pipeline name
        self._m_shard = None   # nns_shard_count gauge (mesh= filters only)

    def _obs_invoke(self):
        """Filter-specific metrics. ``nns_tensor_filter_invoke_seconds``
        times ONLY the backend invoke (the element-level chain histogram
        includes the downstream push); opens/reloads count backend
        lifecycle events (an open implies an XLA compile on jit
        backends)."""
        if self._m_invoke is None:
            reg = get_registry()
            labels = self._obs_labels()
            self._m_invoke = {
                "invoke": reg.histogram(
                    "nns_tensor_filter_invoke_seconds",
                    "Backend invoke() latency (dispatch->result handle)",
                    **labels),
                "opens": reg.counter(
                    "nns_tensor_filter_opens_total",
                    "Backend opens (first open compiles on jit backends)",
                    **labels),
                "reloads": reg.counter(
                    "nns_tensor_filter_reloads_total",
                    "Hot model reloads (RELOAD_MODEL)", **labels),
                "qos_drops": reg.counter(
                    "nns_tensor_filter_qos_drops_total",
                    "Invokes skipped by throttle/QoS", **labels),
            }
        return self._m_invoke

    def obs_snapshot(self):
        out = super().obs_snapshot()
        if self._m_invoke is not None:
            h = self._m_invoke["invoke"]
            if h.count:
                out["invoke_p50_ms"] = round(h.percentile(50) * 1e3, 3)
                out["invoke_p99_ms"] = round(h.percentile(99) * 1e3, 3)
            out["qos_drops"] = int(self._m_invoke["qos_drops"].value)
        out.update(self._window.snapshot())
        return out

    def _combination(self, key: str):
        """Parsed input/output combination, cached off the hot path."""
        if key not in self._comb_cache:
            self._comb_cache[key] = _parse_combination(self.get_property(key))
        return self._comb_cache[key]

    def property_changed(self, key):
        if key in ("input_combination", "output_combination"):
            self._comb_cache.pop(key, None)

    # -- backend lifecycle ---------------------------------------------------
    def _open_fw(self) -> FilterFramework:
        """Open the backend once (reference
        gst_tensor_filter_common_open_fw, tensor_filter_common.c:2394)."""
        if self.fw is not None:
            return self.fw
        fw_name = self.get_property("framework") or "auto"
        model = self.get_property("model")
        if fw_name == "auto":
            if model is None:
                raise ValueError(f"{self.name}: framework=auto needs a model")
            fw_name = detect_framework(model)
            if fw_name is None:
                raise ValueError(
                    f"{self.name}: cannot detect framework for {model!r}"
                )
            self.log.info("framework=auto resolved to %s", fw_name)
        factory = get_subplugin(FILTER, fw_name)
        if factory is None:
            raise ValueError(f"{self.name}: no filter backend {fw_name!r}")
        fw = factory()
        props = FilterProperties(
            model=model,
            custom=self.get_property("custom"),
            accelerator=self.get_property("accelerator"),
            mesh=self.get_property("mesh") or self.get_property("shard"),
            input_info=self._forced_info("input", "inputtype"),
            output_info=self._forced_info("output", "outputtype"),
            is_updatable=bool(self.get_property("is_updatable")),
            shared_key=self.get_property("shared_tensor_filter_key"),
        )
        fi = _faults.ACTIVE
        if fi is not None:
            # chaos hook: an injected open failure — kind=oom models the
            # weight load losing the HBM allocation race
            fi.check("filter.open")
        fw.open(props)
        self.fw = fw
        self._obs_invoke()["opens"].inc()
        plan = getattr(fw, "_mesh_plan", None)
        if plan is not None and self._m_shard is None:
            # nns_shard_count{filter=...}: how many chips this filter's
            # serving mesh spans (0/absent = single-device). Exported on
            # /metrics[.json] and federated by name like every gauge.
            n = int(plan.shard_count)
            self._m_shard = get_registry().gauge(
                "nns_shard_count",
                "Devices in this filter's serving mesh (mesh= property)",
                fn=lambda _n=n: float(_n),
                pipeline=getattr(self.pipeline, "name", "") or "",
                filter=self.name)
        return fw

    def _forced_info(self, dim_key: str, type_key: str) -> Optional[TensorsInfo]:
        dims = self.get_property(dim_key)
        types = self.get_property(type_key)
        if dims is None or types is None:
            return None
        return TensorsInfo.from_str(str(dims), str(types))

    def start(self):
        super().start()
        self._open_fw()

    def stop(self):
        # fence outstanding dispatches before the backend (whose params
        # they read) closes; a poisoned batch must not abort teardown
        self._window.drain(on_error="log")
        if self.fw is not None:
            self.fw.close()
            self.fw = None
        super().stop()

    def handle_eos(self):
        # EOS flush: fence the whole window before EOS crosses downstream
        self._window.drain()

    # -- negotiation ---------------------------------------------------------
    def transform_caps(self, pad, caps):
        cfg = TensorsConfig.from_caps(caps)
        fw = self._open_fw()
        in_info, out_info = fw.get_model_info()
        # the model sees the combination-selected subset, so compare that
        in_comb = self._combination("input_combination")
        model_in_cfg_info = cfg.info
        if in_comb is not None and cfg.info.is_valid():
            model_in_cfg_info = TensorsInfo(
                [cfg.info[i] for _, i in in_comb]
            )
        if model_in_cfg_info.is_valid() and in_info is not None and \
                not model_in_cfg_info.is_equal(in_info):
            raise ValueError(
                f"{self.name}: incoming tensors {model_in_cfg_info!r} do "
                f"not match model input {in_info!r}"
            )
        self._in_model_info = in_info or (
            model_in_cfg_info if model_in_cfg_info.is_valid() else None
        )
        self._in_full_info = cfg.info if cfg.info.is_valid() else None
        if out_info is None:
            if self._in_model_info is None:
                # flexible/dimless input caps + shape-polymorphic model:
                # defer — the first buffer's actual shapes negotiate
                # (reference flexible-tensor streams, e.g. downstream of
                # tensor_query_serversrc, carry per-buffer dims)
                from nnstreamer_tpu.tensors.types import TensorFormat

                self._out_model_info = None
                return TensorsConfig(format=TensorFormat.FLEXIBLE,
                                     rate=cfg.rate).to_caps()
            out_info = fw.set_input_info(self._in_model_info)
        self._out_model_info = out_info
        final = self._combined_out_info(out_info)
        return TensorsConfig(info=final, rate=cfg.rate).to_caps()

    def _combined_out_info(self, out_info: TensorsInfo) -> TensorsInfo:
        comb = self._combination("output_combination")
        if comb is None:
            return out_info
        in_info = self._in_full_info or self._in_model_info
        infos = []
        for kind, idx in comb:
            infos.append(out_info[idx] if kind == "o" else in_info[idx])
        return TensorsInfo(infos)

    def src_event(self, pad, event):
        """Throttle QoS from downstream (tensor_rate throttle=true,
        gsttensorrate.c:27-36): adopt the target interval and consume the
        event — the filter is the expensive element the QoS targets."""
        from nnstreamer_tpu.pipeline.element import QosEvent

        if isinstance(event, QosEvent):
            self._qos_interval_s = event.target_interval_ns / 1e9
            return
        super().src_event(pad, event)

    # -- hot path ------------------------------------------------------------
    def chain(self, pad, buf):
        obs = self._obs_invoke()
        throttle = int(self.get_property("throttle"))
        # min invoke interval: own throttle prop and downstream QoS combine
        if self._qos_throttled(1.0 / throttle if throttle > 0 else 0.0):
            obs["qos_drops"].inc()
            return None  # QoS drop (tensor_filter.c:426)
        fw = self.fw or self._open_fw()

        if not fw.KEEP_ON_DEVICE and isinstance(buf, DeviceBuffer):
            # host-only backend consuming a resident buffer: one cached
            # materialization up front (reuses a prefetch queue's
            # pre-upload host view when one rode along) instead of the
            # per-tensor asarray below
            buf = buf.to_host()

        in_comb = self._combination("input_combination")
        if in_comb is not None:
            model_inputs = [buf.tensors[i] for _, i in in_comb]
        else:
            model_inputs = buf.tensors

        if self._out_model_info is None and self._in_model_info is None:
            # deferred negotiation (flexible input): first buffer fixes the
            # model's shapes
            derived = TensorsInfo.from_arrays(model_inputs)
            self._in_model_info = derived
            self._out_model_info = fw.set_input_info(derived)

        if not fw.KEEP_ON_DEVICE:
            # host-only backend: its invoke() contract IS host arrays, so
            # this materialization is the backend boundary, not a hidden
            # fence the dispatch window could have avoided
            model_inputs = [
                np.asarray(x)  # nns-lint: disable=NNS107 -- host backend
                if not isinstance(x, np.ndarray) else x
                for x in model_inputs]

        tl = _timeline.ACTIVE
        seq = buf.meta.get(_timeline.TRACE_SEQ_META) \
            if tl is not None else None
        plan = getattr(fw, "_mesh_plan", None)
        if plan is not None:
            # unfused mesh invoke (e.g. the budgeted-weights path region
            # fusion skips): place the batch HERE, where the frame's
            # trace identity is known, so the placement wait lands in
            # the ledger as its own `shard` stage — the fused path does
            # the same in FusedRegion.chain. The backend's own
            # place_batch then sees matched arrays and moves nothing.
            from nnstreamer_tpu.parallel import serve as _serve

            t_sh0 = _time.monotonic()
            model_inputs = [_serve.place_batch(x, plan)
                            for x in model_inputs]
            if tl is not None and seq is not None:
                tl.span("shard", seq, t_sh0, _time.monotonic(),
                        track=self.name)

        fi = _faults.ACTIVE
        if fi is not None:
            # chaos hook, BEFORE the stash pop: a retrying error policy
            # re-enters chain with the buffer's meta intact
            fi.check("filter.invoke",
                     seq=buf.meta.get(_timeline.TRACE_SEQ_META))

        from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META

        stash = buf.meta.pop(POOL_STASH_META, None)
        t0 = _time.monotonic()
        try:
            outputs = fw.invoke(model_inputs)
        except Exception:
            if stash:
                # restore the stash so a retrying error policy (or the
                # next consumer) still releases the pooled staging
                # arrays at a fence — a lost stash pins slabs forever
                buf.meta[POOL_STASH_META] = stash
            raise
        dt = _time.monotonic() - t0
        obs["invoke"].observe(dt)
        if tl is not None and seq is not None:
            tl.span("device", seq, t0, t0 + dt, track=self.name)
        sched = getattr(self.pipeline, "_slo_scheduler", None)
        if sched is not None:
            # feed the admission controller's service-rate EWMA; the
            # leading dim of a micro-batched input is its frame count
            # (frames-dim concat), a single frame estimates as 1
            shape = getattr(model_inputs[0], "shape", None) \
                if model_inputs else None
            frames = shape[0] if shape else 1
            sched.observe_service(dt, frames=int(frames))

        out_comb = self._combination("output_combination")
        if out_comb is not None:
            final = [outputs[i] if k == "o" else buf.tensors[i]
                     for k, i in out_comb]
        else:
            final = list(outputs)
        if stash or any(not isinstance(t, np.ndarray) for t in final):
            # bounded async dispatch: register the outstanding batch; the
            # oldest fences only when more than `inflight` are in flight,
            # and pooled staging inputs recycle at that fence point.
            # Host-only results with no stash skip the window entirely —
            # nothing is outstanding for them.
            self._window.admit(final, stash, frame=seq)
        out_buf = buf.with_tensors(final)
        if plan is not None:
            # NamedSharding-stamped hand-off: downstream sharded
            # consumers (and verify_mesh_boundaries' runtime twin,
            # place_batch) can see which mesh this batch already lives on
            from nnstreamer_tpu.parallel import serve as _serve

            out_buf.meta[_serve.MESH_SPEC_META] = plan.spec
        if peer_device_capable(self.srcpad):
            # device-capable downstream: keep the result resident (no-op
            # for host outputs or when NNSTPU_RESIDENT=0)
            out_buf = as_device_buffer(out_buf)
        return self.srcpad.push(out_buf)

    # -- region fusion (pipeline/fuse.py) ------------------------------------
    def device_stage(self):
        """Fusible when the backend can hand over a pure jittable stage and
        no host-side per-frame control flow is configured (throttle drops
        are data/time-dependent host decisions)."""
        if int(self.get_property("throttle")) > 0:
            return None
        fw = self.fw
        stage_getter = getattr(fw, "device_stage", None)
        if fw is None or stage_getter is None:
            return None
        backend_stage = stage_getter()
        if backend_stage is None:
            return None
        from nnstreamer_tpu.pipeline.fuse import DeviceStage

        in_comb = self._combination("input_combination")
        out_comb = self._combination("output_combination")
        inner = backend_stage.fn

        def fn(consts, tensors):
            model_in = [tensors[i] for _, i in in_comb] if in_comb \
                else tensors
            outs = inner(consts, model_in)
            if out_comb:
                return [outs[i] if k == "o" else tensors[i]
                        for k, i in out_comb]
            return list(outs)

        key = None if backend_stage.key is None else (
            "tensor_filter", backend_stage.key,
            tuple(in_comb or ()), tuple(out_comb or ()),
        )
        return DeviceStage(consts=backend_stage.consts, fn=fn, key=key,
                           mesh=backend_stage.mesh)

    # -- events --------------------------------------------------------------
    def sink_event(self, pad, event: Event):
        if isinstance(event, CustomEvent) and event.name == "reload_model":
            if self.fw is not None:
                self.fw.handle_event("reload_model", event.data)
                self._obs_invoke()["reloads"].inc()
                self.log.info("model reloaded")
                # a filter folded into a whole-graph program keeps
                # serving the stale compiled weights until its region
                # re-pulls stages: invalidate here exactly as the
                # app-facing reload_model() path does (the re-trace is
                # counted in nns_fuse_retraces_total at trace time)
                self._invalidate_region()
            return  # consumed
        super().sink_event(pad, event)

    def _invalidate_region(self) -> None:
        region = getattr(self, "_fused_region", None)
        if region is not None:
            region.invalidate()

    def reload_model(self, model: Optional[str] = None) -> None:
        """App-facing hot reload (reference RELOAD_MODEL event)."""
        data = {"model": model} if model else {}
        if model:
            self._props["model"] = model
        if self.fw is not None:
            self.fw.handle_event("reload_model", data)
            self._obs_invoke()["reloads"].inc()
        self._invalidate_region()
