"""tensor_src_grpc / tensor_sink_grpc — gRPC stream endpoints as elements.

Reference: ``ext/nnstreamer/tensor_source/tensor_src_grpc.c`` (515 LoC) and
``ext/nnstreamer/tensor_sink/tensor_sink_grpc.c`` (396 LoC): each element
runs either as a gRPC *server* or *client* (``server`` property), src
yields buffers received over TensorService, sink ships buffers out;
``idl`` selects the payload encoding: protobuf | flexbuf | flatbuf
(reference-layout, interoperable with a reference nnstreamer peer;
rank-4 normalizing, no pts) or nnstpu-flex (framework-native framing —
carries pts, allows rank>4/fp16, our peers only).

Roles (mirroring the reference's mode matrix):
- src  + server=true : hosts the service; remote clients stream tensors IN
  via SendTensors and the element pushes them downstream.
- src  + server=false: connects out and consumes the remote's RecvTensors
  stream.
- sink + server=true : hosts the service; remote clients pull this
  pipeline's output via RecvTensors.
- sink + server=false: connects out and ships buffers via SendTensors.
"""

from __future__ import annotations

import queue as _queue
from typing import Optional

from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(ELEMENT, "tensor_src_grpc")
class TensorSrcGrpc(SourceElement):
    ELEMENT_NAME = "tensor_src_grpc"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "host": "127.0.0.1",
        "port": 0,
        "server": True,
        "idl": "protobuf",
        "num_buffers": -1,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._q: _queue.Queue = _queue.Queue(maxsize=64)
        self._server = None
        self._client = None
        self._recv_iter = None
        self._count = 0

    @property
    def port(self) -> int:
        """Bound port (server mode; useful with port=0 auto-pick)."""
        return self._server.port if self._server else \
            int(self.get_property("port"))

    def start(self):
        super().start()
        from nnstreamer_tpu.query.grpc_bridge import (
            TensorServiceClient,
            TensorServiceServer,
        )

        if self.get_property("server"):
            self._server = TensorServiceServer(
                self.get_property("host"), int(self.get_property("port")),
                idl=self.get_property("idl"), on_recv=self._q.put,
            ).start()
        else:
            self._client = TensorServiceClient(
                self.get_property("host"), int(self.get_property("port")),
                idl=self.get_property("idl"),
            ).wait_ready()
            self._recv_iter = iter(self._client.recv_stream())

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self._count:
            return None
        if self._recv_iter is not None:
            try:
                buf = next(self._recv_iter)
            except StopIteration:
                return None
            except Exception:  # noqa: BLE001 — channel torn down at stop
                return None
            self._count += 1
            return buf
        while not self._stop_evt.is_set():
            try:
                buf = self._q.get(timeout=0.1)
                self._count += 1
                return buf
            except _queue.Empty:
                continue
        return None

    def stop(self):
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._client is not None:
            self._client.close()
            self._client = None
        super().stop()


@subplugin(ELEMENT, "tensor_sink_grpc")
class TensorSinkGrpc(Element):
    ELEMENT_NAME = "tensor_sink_grpc"
    PROPERTIES = {
        **Element.PROPERTIES,
        "host": "127.0.0.1",
        "port": 0,
        "server": False,
        "idl": "protobuf",
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._server = None
        self._client = None
        self._sendq: Optional[_queue.Queue] = None
        self._sender = None

    @property
    def port(self) -> int:
        return self._server.port if self._server else \
            int(self.get_property("port"))

    def start(self):
        super().start()
        from nnstreamer_tpu.query.grpc_bridge import (
            TensorServiceClient,
            TensorServiceServer,
        )

        if self.get_property("server"):
            self._server = TensorServiceServer(
                self.get_property("host"), int(self.get_property("port")),
                idl=self.get_property("idl"),
            ).start()
        else:
            import threading

            self._client = TensorServiceClient(
                self.get_property("host"), int(self.get_property("port")),
                idl=self.get_property("idl"),
            ).wait_ready()
            self._sendq = _queue.Queue(maxsize=64)

            def gen():
                while True:
                    item = self._sendq.get()
                    if item is None:
                        return
                    yield item

            # one long-lived SendTensors stream fed by chain()
            self._sender = threading.Thread(
                target=lambda: self._client.send_stream(gen()),
                name=f"{self.name}-send", daemon=True)
            self._sender.start()

    def chain(self, pad, buf):
        buf = buf.to_host()
        if self._server is not None:
            self._server.send(buf)
        elif self._sendq is not None:
            self._sendq.put(buf)
        return FlowReturn.OK

    def stop(self):
        if self._sendq is not None:
            self._sendq.put(None)
            if self._sender is not None:
                self._sender.join(timeout=5)
            self._sendq = self._sender = None
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        super().stop()
