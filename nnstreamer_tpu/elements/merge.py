"""tensor_merge — N single tensors → ONE tensor along a dimension.

Reference: ``gst/nnstreamer/elements/gsttensormerge.c`` (883 LoC), mode
``linear`` with option = dim index to concatenate along (innermost-first
dim order), under the shared sync policies. On TPU this is the batcher:
``tensor_mux``'d streams merged on a new outer dim become ONE batched XLA
invoke downstream.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from nnstreamer_tpu.elements.collect import CollectPads
from nnstreamer_tpu.pipeline.element import (
    CapsEvent,
    Element,
    EosEvent,
    FlowReturn,
)
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer, is_device_array


@subplugin(ELEMENT, "tensor_merge")
class TensorMerge(Element):
    ELEMENT_NAME = "tensor_merge"
    PROPERTIES = {**Element.PROPERTIES, "mode": "linear", "option": "0",
                  "sync_mode": "slowest", "sync_option": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_src_pad("src")
        self._collect: Optional[CollectPads] = None
        self._pad_index = {}

    def request_sink_pad(self):
        pad = self.add_sink_pad(f"sink_{len(self.sinkpads)}")
        self._pad_index[pad] = len(self.sinkpads) - 1
        return pad

    def _get_collect(self):
        if self._collect is None:
            from nnstreamer_tpu.obs import get_registry

            hist = get_registry().histogram(
                "nns_tensor_merge_sync_wait_seconds",
                "Frame-set assembly wait under the pad-sync policy",
                **self._obs_labels())
            self._collect = CollectPads(
                num_pads=len(self.sinkpads),
                policy=self.get_property("sync_mode"),
                option=self.get_property("sync_option"),
                on_ready=self._emit,
                observe_wait=hist.observe,
            )
        return self._collect

    def chain(self, pad, buf):
        self._get_collect().push(self._pad_index[pad], buf)
        return FlowReturn.OK

    def _emit(self, frame):
        arrays = [buf.tensors[0] for _, buf in frame]
        dim_idx = int(self.get_property("option"))
        rank = arrays[0].ndim
        axis = rank - 1 - dim_idx  # dim order (innermost-first) → numpy axis
        if any(is_device_array(a) for a in arrays):
            import jax.numpy as jnp

            merged = jnp.concatenate(arrays, axis=axis)
        else:
            merged = np.concatenate(arrays, axis=axis)
        pts = max((b.pts or 0) for _, b in frame)
        if self.srcpad.caps is None:
            from nnstreamer_tpu.tensors.types import TensorsConfig

            self.srcpad.set_caps(TensorsConfig.from_arrays([merged]).to_caps())
        self.srcpad.push(TensorBuffer([merged], pts=pts))

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            return  # output caps derived from first merged frame
        if isinstance(event, EosEvent):
            if self._collect is not None and \
                    self._collect.set_eos(self._pad_index[pad]):
                self.srcpad.push_event(event)
            elif self._collect is None and all(p.eos for p in self.sinkpads):
                self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)
