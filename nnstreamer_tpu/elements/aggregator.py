"""tensor_aggregator — temporal frame aggregation / dis-aggregation.

Reference: ``gst/nnstreamer/elements/gsttensoraggregator.c`` (1081 LoC,
tensor_aggregator/README.md): collects ``frames-in`` frames per input
buffer, emits ``frames-out`` frames per output, advancing by
``frames-flush`` (sliding window when flush < out), concatenating along
``frames-dim``. This is the stream-side micro-batching / sequence-window
primitive (SURVEY §2.4.3) — e.g. windowing audio for a sequence model.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer, is_device_array


@subplugin(ELEMENT, "tensor_aggregator")
class TensorAggregator(Element):
    ELEMENT_NAME = "tensor_aggregator"
    PROPERTIES = {
        **Element.PROPERTIES,
        "frames_in": 1,
        "frames_out": 1,
        "frames_flush": 0,   # 0 → == frames_out (no overlap)
        "frames_dim": 0,     # innermost-first dim index to aggregate along
        "concat": True,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        #: one window per tensor position in the frame — every tensor of a
        #: multi-tensor stream is aggregated, none silently dropped
        self._windows: List[List[np.ndarray]] = []
        self._pts: Optional[int] = None
        #: capture timestamps of the unit frames in flight, parallel to the
        #: windows — emitted as meta["create_ts"] so end-to-end latency
        #: under micro-batching includes each frame's batch-window wait
        self._create_ts: List[float] = []

    def transform_caps(self, pad, caps):
        return None  # announced from the first output (shape changes)

    def _axis(self, arr) -> int:
        return arr.ndim - 1 - int(self.get_property("frames_dim"))

    def chain(self, pad, buf):
        fin = int(self.get_property("frames_in"))
        fout = int(self.get_property("frames_out"))
        flush = int(self.get_property("frames_flush")) or fout
        if not buf.tensors:
            return None  # empty frame: nothing to window (and `all([])`
            # below would spin forever)
        if not self._windows:
            self._windows = [[] for _ in buf.tensors]
        elif len(buf.tensors) != len(self._windows):
            raise ValueError(
                f"tensor_aggregator: frame has {len(buf.tensors)} tensors, "
                f"stream started with {len(self._windows)}"
            )
        if self._pts is None:
            self._pts = buf.pts
        n = max(fin, 1)
        # validate every tensor BEFORE mutating windows or stamps: a
        # mid-loop failure would leave them desynchronized for any caller
        # that catches the error and keeps streaming
        for arr in buf.tensors:
            axis = self._axis(arr)
            if arr.shape[axis] % n:
                raise ValueError(
                    f"tensor_aggregator: dim "
                    f"{self.get_property('frames_dim')} size "
                    f"{arr.shape[axis]} not divisible by frames-in {n}"
                )
        stamps = buf.create_stamps()
        if stamps:
            # exactly one stamp per unit frame keeps the stamp list in
            # lockstep with the windows; when the carried stamp count
            # doesn't match the frames_in split (e.g. a muxed buffer with
            # one stamp per input stream), use the EARLIEST stamp for all
            # of them — conservative (reports the longest latency)
            if len(stamps) != n:
                stamps = [min(stamps)] * n
        if stamps or self._create_ts:
            # mixed stamped/unstamped upstreams (frames pushed straight
            # into srcpad.push interleaved with SourceElement frames)
            # must not shift stamp→window attribution: pad any historical
            # deficit and this buffer's missing stamps with None
            # placeholders so indices stay aligned (filtered at emit)
            deficit = max(0, len(self._windows[0]) - len(self._create_ts))
            self._create_ts.extend([None] * deficit)
            self._create_ts.extend(stamps if stamps else [None] * n)
        for ti, arr in enumerate(buf.tensors):
            axis = self._axis(arr)
            # split the incoming tensor into its `frames_in` unit frames
            per = arr.shape[axis] // n
            for k in range(n):
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(k * per, (k + 1) * per)
                self._windows[ti].append(arr[tuple(sl)])
        ret = None
        while all(len(w) >= fout for w in self._windows):
            outs = []
            for w in self._windows:
                chunk = w[:fout]
                axis = self._axis(chunk[0])
                if self.get_property("concat"):
                    if is_device_array(chunk[0]):
                        import jax.numpy as jnp

                        outs.append(jnp.concatenate(chunk, axis=axis))
                    else:
                        outs.append(np.concatenate(chunk, axis=axis))
                else:
                    # concat=false: collected frames stay separate tensors
                    # (reference tensor_aggregator concat property)
                    outs.extend(chunk)
            if self.srcpad.caps is None:
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self.srcpad.set_caps(
                    TensorsConfig.from_arrays(outs).to_caps()
                )
            meta = {}
            if self._create_ts:
                out_ts = [s for s in self._create_ts[:fout]
                          if s is not None]
                if out_ts:
                    meta["create_ts"] = out_ts
            ret = self.srcpad.push(
                TensorBuffer(outs, pts=self._pts, meta=meta)
            )
            self._windows = [w[flush:] for w in self._windows]
            self._create_ts = self._create_ts[flush:]
            self._pts = buf.pts
        return ret

    def handle_eos(self):
        self._windows.clear()
        self._create_ts.clear()
        self._pts = None
