"""tensor_aggregator — temporal frame aggregation / dis-aggregation.

Reference: ``gst/nnstreamer/elements/gsttensoraggregator.c`` (1081 LoC,
tensor_aggregator/README.md): collects ``frames-in`` frames per input
buffer, emits ``frames-out`` frames per output, advancing by
``frames-flush`` (sliding window when flush < out), concatenating along
``frames-dim``. This is the stream-side micro-batching / sequence-window
primitive (SURVEY §2.4.3) — e.g. windowing audio for a sequence model.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer, is_device_array


@subplugin(ELEMENT, "tensor_aggregator")
class TensorAggregator(Element):
    ELEMENT_NAME = "tensor_aggregator"
    PROPERTIES = {
        **Element.PROPERTIES,
        "frames_in": 1,
        "frames_out": 1,
        "frames_flush": 0,   # 0 → == frames_out (no overlap)
        "frames_dim": 0,     # innermost-first dim index to aggregate along
        "concat": True,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._window: List[np.ndarray] = []  # unit frames along frames_dim
        self._pts: Optional[int] = None

    def transform_caps(self, pad, caps):
        return None  # announced from the first output (shape changes)

    def _axis(self, arr) -> int:
        return arr.ndim - 1 - int(self.get_property("frames_dim"))

    def chain(self, pad, buf):
        fin = int(self.get_property("frames_in"))
        fout = int(self.get_property("frames_out"))
        flush = int(self.get_property("frames_flush")) or fout
        arr = buf.tensors[0]
        axis = self._axis(arr)
        if self._pts is None:
            self._pts = buf.pts
        # split the incoming buffer into its `frames_in` unit frames
        n = max(fin, 1)
        if arr.shape[axis] % n:
            raise ValueError(
                f"tensor_aggregator: dim {self.get_property('frames_dim')} "
                f"size {arr.shape[axis]} not divisible by frames-in {n}"
            )
        per = arr.shape[axis] // n
        for k in range(n):
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(k * per, (k + 1) * per)
            self._window.append(arr[tuple(sl)])
        ret = None
        while len(self._window) >= fout:
            chunk = self._window[:fout]
            if self.get_property("concat"):
                if is_device_array(chunk[0]):
                    import jax.numpy as jnp

                    outs = [jnp.concatenate(chunk, axis=axis)]
                else:
                    outs = [np.concatenate(chunk, axis=axis)]
            else:
                # concat=false: collected frames stay separate tensors
                # (reference tensor_aggregator concat property)
                outs = list(chunk)
            if self.srcpad.caps is None:
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self.srcpad.set_caps(
                    TensorsConfig.from_arrays(outs).to_caps()
                )
            ret = self.srcpad.push(
                TensorBuffer(outs, pts=self._pts)
            )
            self._window = self._window[flush:]
            self._pts = buf.pts
        return ret

    def handle_eos(self):
        self._window.clear()
        self._pts = None
