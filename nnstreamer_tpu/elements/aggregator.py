"""tensor_aggregator — temporal frame aggregation / dis-aggregation.

Reference: ``gst/nnstreamer/elements/gsttensoraggregator.c`` (1081 LoC,
tensor_aggregator/README.md): collects ``frames-in`` frames per input
buffer, emits ``frames-out`` frames per output, advancing by
``frames-flush`` (sliding window when flush < out), concatenating along
``frames-dim``. This is the stream-side micro-batching / sequence-window
primitive (SURVEY §2.4.3) — e.g. windowing audio for a sequence model.

``latency-budget-ms`` adds latency-budget adaptive batching on top: a
window that would otherwise hold frames past the budget waiting to fill
is flushed EARLY, padded to ``frames-out`` by repeating the last frame so
the downstream jitted program keeps its single compiled shape (no
per-partial-size recompiles). The padded output carries
``meta["valid_frames"]=k``; ``tensor_sink`` slices the padding off at
materialization and latency stamps cover only the real frames. This is
the per-frame-latency half of the north-star metric: the reference's
per-frame path (tensor_filter.c:349-423) never batches, so its p50 is
one service time — budget mode bounds the admission wait a micro-batched
stream adds while keeping the batched throughput path intact (full
windows are never padded, and a saturated stream fills windows faster
than any budget fires).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer, is_device_array

log = get_logger("elements.aggregator")


@subplugin(ELEMENT, "tensor_aggregator")
class TensorAggregator(Element):
    ELEMENT_NAME = "tensor_aggregator"
    #: batch-drain opt-in: a queue backlog arrives as one list, windowed
    #: under ONE lock acquisition (see chain_list)
    HANDLES_LIST = True
    DEVICE_PASSTHROUGH = True  # device windows concat via jnp, host via np
    PROPERTIES = {
        **Element.PROPERTIES,
        "frames_in": 1,
        "frames_out": 1,
        "frames_flush": 0,   # 0 → == frames_out (no overlap)
        "frames_dim": 0,     # innermost-first dim index to aggregate along
        "concat": True,
        # >0: flush a PARTIAL window (padded to frames-out, with
        # meta["valid_frames"]) once the oldest queued frame has waited
        # this many ms — latency-budget adaptive batching. A budget
        # flush emits everything queued (sliding-window overlap does not
        # apply to it) and the remaining tail is flushed at EOS.
        "latency_budget_ms": 0,
        # partial-flush padding placement: false (default) pads on host
        # to frames-out — universal, but the pad rows cross the H2D link
        # too. true emits only the k real frames plus
        # meta["pad_rows"]; a downstream prefetch-device queue applies
        # the zero-pad ON DEVICE (tensors/buffer.py pad_rows_device), so
        # the wire carries k frames while the jitted filter still sees
        # its one compiled frames-out shape. Requires such a queue
        # downstream — without one the filter sees [k] and recompiles
        # per distinct k.
        "pad_device": False,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        #: one window per tensor position in the frame — every tensor of a
        #: multi-tensor stream is aggregated, none silently dropped
        self._windows: List[List[np.ndarray]] = []
        self._pts: Optional[int] = None
        #: capture timestamps of the unit frames in flight, parallel to the
        #: windows — emitted as meta["create_ts"] so end-to-end latency
        #: under micro-batching includes each frame's batch-window wait
        self._create_ts: List[float] = []
        #: admission stamps (meta["admitted_t"] from a stamp-admission
        #: queue upstream), in lockstep with the windows like _create_ts
        #: — emitted as meta["admitted_ts"] so the sink's served-traffic
        #: latency population survives micro-batching
        self._admit_ts: List[float] = []
        #: trace seqs of the unit frames in flight (timeline active
        #: only), same lockstep discipline as _create_ts — a combined
        #: window adopts its earliest constituent's frame identity
        self._tl_seqs: List[Optional[int]] = []
        #: budget clock per queued unit frame: its create stamp when one
        #: flowed (end-to-end budget), else its aggregator arrival time
        self._held_since: List[float] = []
        #: serializes chain() with the budget flusher thread — both push
        #: downstream, and a flush must not interleave with window append
        self._lock = threading.RLock()
        self._flusher: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    def start(self):
        super().start()
        budget = float(self.get_property("latency_budget_ms"))
        if budget > 0:
            self._stop_evt.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, args=(budget / 1e3,),
                daemon=True, name=f"{self.name}-budget")
            self._flusher.start()

    def stop(self):
        self._stop_evt.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            self._flusher = None
        super().stop()

    def note_mesh_quantum(self, quantum: int) -> None:
        """Mesh-wide batch forming (parallel/serve.py): round frames-out
        up to a multiple of the pipeline's dp shard count so every full
        window this former emits splits evenly across the mesh. A
        non-multiple window is still legal — the sharded region falls
        back to a replicated invoke for it — but it serializes the batch
        onto one shard, so the former should not produce one by
        construction. Called by Pipeline.start() once the sharded plan
        is known; pass-through configs (frames-out == 1) are left alone
        because the user asked for per-frame service, not batching."""
        q = max(1, int(quantum))
        fout = int(self.get_property("frames_out"))
        if q <= 1 or fout <= 1 or fout % q == 0:
            return
        rounded = ((fout + q - 1) // q) * q
        log.info("%s: frames-out %d -> %d (mesh shard quantum %d)",
                 self.name, fout, rounded, q)
        self.set_property("frames_out", rounded)

    def transform_caps(self, pad, caps):
        return None  # announced from the first output (shape changes)

    def _axis(self, arr) -> int:
        return arr.ndim - 1 - int(self.get_property("frames_dim"))

    def chain(self, pad, buf):
        with self._lock:
            return self._chain_locked(pad, buf)

    def chain_list(self, pad, bufs):
        """Batch-drain fast path: the whole queue backlog windows under
        one lock acquisition (the flusher thread contends once per
        backlog instead of once per frame)."""
        ret = None
        with self._lock:
            for b in bufs:
                ret = self._chain_locked(pad, b)
        return ret

    def _chain_locked(self, pad, buf):
        fin = int(self.get_property("frames_in"))
        fout = int(self.get_property("frames_out"))
        flush = int(self.get_property("frames_flush")) or fout
        if not buf.tensors:
            return None  # empty frame: nothing to window (and `all([])`
            # below would spin forever)
        if not self._windows:
            self._windows = [[] for _ in buf.tensors]
        elif len(buf.tensors) != len(self._windows):
            raise ValueError(
                f"tensor_aggregator: frame has {len(buf.tensors)} tensors, "
                f"stream started with {len(self._windows)}"
            )
        if self._pts is None:
            self._pts = buf.pts
        n = max(fin, 1)
        # validate every tensor BEFORE mutating windows or stamps: a
        # mid-loop failure would leave them desynchronized for any caller
        # that catches the error and keeps streaming
        for arr in buf.tensors:
            axis = self._axis(arr)
            if arr.shape[axis] % n:
                raise ValueError(
                    f"tensor_aggregator: dim "
                    f"{self.get_property('frames_dim')} size "
                    f"{arr.shape[axis]} not divisible by frames-in {n}"
                )
        stamps = buf.create_stamps()
        if stamps:
            # exactly one stamp per unit frame keeps the stamp list in
            # lockstep with the windows; when the carried stamp count
            # doesn't match the frames_in split (e.g. a muxed buffer with
            # one stamp per input stream), use the EARLIEST stamp for all
            # of them — conservative (reports the longest latency)
            if len(stamps) != n:
                stamps = [min(stamps)] * n
        if stamps or self._create_ts:
            # mixed stamped/unstamped upstreams (frames pushed straight
            # into srcpad.push interleaved with SourceElement frames)
            # must not shift stamp→window attribution: pad any historical
            # deficit and this buffer's missing stamps with None
            # placeholders so indices stay aligned (filtered at emit)
            deficit = max(0, len(self._windows[0]) - len(self._create_ts))
            self._create_ts.extend([None] * deficit)
            self._create_ts.extend(stamps if stamps else [None] * n)
        if _timeline.ACTIVE is not None or self._tl_seqs:
            deficit = max(0, len(self._windows[0]) - len(self._tl_seqs))
            self._tl_seqs.extend([None] * deficit)
            self._tl_seqs.extend(
                [buf.meta.get(_timeline.TRACE_SEQ_META)] * n)
        adm = buf.meta.get("admitted_t")
        if adm is not None or self._admit_ts:
            # same alignment discipline as _create_ts: the buffer's one
            # admission stamp covers each of its unit frames
            deficit = max(0, len(self._windows[0]) - len(self._admit_ts))
            self._admit_ts.extend([None] * deficit)
            self._admit_ts.extend([adm] * n)
        budget = float(self.get_property("latency_budget_ms"))
        if budget > 0:
            now = time.monotonic()
            self._held_since.extend(
                (stamps[i] if stamps and stamps[i] is not None else now)
                for i in range(n))
        for ti, arr in enumerate(buf.tensors):
            axis = self._axis(arr)
            # split the incoming tensor into its `frames_in` unit frames
            per = arr.shape[axis] // n
            for k in range(n):
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(k * per, (k + 1) * per)
                self._windows[ti].append(arr[tuple(sl)])
        ret = None
        while all(len(w) >= fout for w in self._windows):
            outs = self._concat_windows(
                [w[:fout] for w in self._windows])
            self._announce_caps(outs)
            meta = {}
            if self._create_ts:
                out_ts = [s for s in self._create_ts[:fout]
                          if s is not None]
                if out_ts:
                    meta["create_ts"] = out_ts
            if self._admit_ts:
                out_adm = [s for s in self._admit_ts[:fout]
                           if s is not None]
                if out_adm:
                    meta["admitted_ts"] = out_adm
            seq = next((s for s in self._tl_seqs[:fout]
                        if s is not None), None)
            if seq is not None:
                meta[_timeline.TRACE_SEQ_META] = seq
            ret = self.srcpad.push(
                TensorBuffer(outs, pts=self._pts, meta=meta)
            )
            self._windows = [w[flush:] for w in self._windows]
            self._create_ts = self._create_ts[flush:]
            self._admit_ts = self._admit_ts[flush:]
            self._tl_seqs = self._tl_seqs[flush:]
            self._held_since = self._held_since[flush:]
            self._pts = buf.pts
        if budget > 0 and self._held_since and \
                time.monotonic() - self._held_since[0] >= budget / 1e3 \
                and self._downstream_ready():
            ret = self._emit_partial() or ret
        return ret

    def _downstream_ready(self) -> bool:
        """Backpressure gate for budget flushes: a partial flush is a
        latency optimization, and it only helps while the downstream can
        absorb the extra dispatch. When the link/device is saturated
        (the downstream queue is full), flushing MORE, SMALLER windows
        compounds the backlog — measured 13x worse p50 on a degraded
        tunnel. Holding instead lets the window fill toward a full
        batch, i.e. budget mode degrades gracefully to plain batching
        under overload. Full windows are exempt: they flush through the
        normal (blocking) path regardless."""
        peer = self.srcpad.peer
        ready = getattr(getattr(peer, "element", None), "accepts_now",
                        None)
        return True if ready is None else bool(ready())

    def _flush_loop(self, budget_s: float):
        """Budget watchdog: chain() only runs on arrivals, so a stalled
        upstream would otherwise hold queued frames past the budget
        forever. Ticks at budget/4 → a frame overstays by at most ~25%."""
        tick = max(budget_s / 4, 0.005)
        while not self._stop_evt.wait(tick):
            with self._lock:
                if self._held_since and \
                        time.monotonic() - self._held_since[0] >= budget_s \
                        and self._downstream_ready():
                    self._emit_partial()

    def _concat_windows(self, chunks):
        """Emit-side payload assembly shared by the full-window and
        budget-flush paths: one concatenated tensor per window
        (concat=true) or the unit frames as separate tensors."""
        outs = []
        for chunk in chunks:
            if self.get_property("concat"):
                axis = self._axis(chunk[0])
                if is_device_array(chunk[0]):
                    import jax.numpy as jnp

                    outs.append(jnp.concatenate(chunk, axis=axis))
                elif all(c.dtype == chunk[0].dtype for c in chunk):
                    # host windows assemble into a recycled staging
                    # buffer (tensors/pool.py): at flagship rates this
                    # concat is the ingest path's one per-window
                    # allocation, and the pooled buffer recycles once
                    # the H2D that consumes it fences downstream
                    from nnstreamer_tpu.tensors.pool import get_pool

                    shape = list(chunk[0].shape)
                    shape[axis] = sum(c.shape[axis] for c in chunk)
                    dst = get_pool().acquire(shape, chunk[0].dtype)
                    np.concatenate(chunk, axis=axis, out=dst)
                    outs.append(dst)
                else:
                    # mixed dtypes promote — let numpy own the result
                    outs.append(np.concatenate(chunk, axis=axis))
            else:
                # concat=false: collected frames stay separate tensors
                # (reference tensor_aggregator concat property)
                outs.extend(chunk)
        return outs

    def _announce_caps(self, outs):
        if self.srcpad.caps is None:
            from nnstreamer_tpu.tensors.types import TensorsConfig

            self.srcpad.set_caps(TensorsConfig.from_arrays(outs).to_caps())

    def _emit_partial(self):
        """Flush the queued k < frames-out frames. With concat=true on a
        leading (axis-0) frame axis the window is padded to frames-out
        (one compiled downstream shape) and ``meta["valid_frames"]=k``
        lets the sink trim the padding; ``pad-device`` defers that pad
        to a downstream prefetch-device queue so only the k real frames
        cross the H2D link. Non-leading concat axes and concat=false
        emit the k real frames UNPADDED (self-describing shapes — the
        sink's axis-0 trim cannot apply there). Caller holds
        ``self._lock``."""
        fout = int(self.get_property("frames_out"))
        k = len(self._windows[0]) if self._windows else 0
        if not k:
            return None
        pad_ok = (self.get_property("concat") and k < fout and
                  self._axis(self._windows[0][0]) == 0)
        # the device-pad path needs announced caps (set below from a
        # host-padded first window)
        on_device_pad = (pad_ok and bool(self.get_property("pad_device"))
                         and self.srcpad.caps is not None)
        pad_n = (fout - k) if (pad_ok and not on_device_pad) else 0
        outs = self._concat_windows(
            [list(w) + [w[-1]] * pad_n for w in self._windows])
        if not on_device_pad:
            self._announce_caps(outs)
        meta = {}
        if pad_ok:
            meta["valid_frames"] = k
            if on_device_pad:
                meta["pad_rows"] = fout - k
        out_ts = [s for s in self._create_ts[:k] if s is not None]
        if out_ts:
            meta["create_ts"] = out_ts
        out_adm = [s for s in self._admit_ts[:k] if s is not None]
        if out_adm:
            meta["admitted_ts"] = out_adm
        seq = next((s for s in self._tl_seqs[:k] if s is not None), None)
        if seq is not None:
            meta[_timeline.TRACE_SEQ_META] = seq
        ret = self.srcpad.push(TensorBuffer(outs, pts=self._pts, meta=meta))
        self._windows = [[] for _ in self._windows]
        self._create_ts = []
        self._admit_ts = []
        self._tl_seqs = []
        self._held_since = []
        self._pts = None
        return ret

    def handle_eos(self):
        with self._lock:
            if float(self.get_property("latency_budget_ms")) > 0:
                # budget mode promises every frame a bounded exit: the
                # partial tail flushes instead of being dropped
                self._emit_partial()
            self._windows.clear()
            self._create_ts.clear()
            self._admit_ts.clear()
            self._tl_seqs.clear()
            self._held_since.clear()
            self._pts = None
