"""join — forward whichever input arrives first (many-to-one switch).

Reference: ``gst/join/gstjoin.c`` (829 LoC): unlike mux, join performs no
synchronization — buffers from all sink pads are forwarded in arrival
order on one src pad (used to reunite exclusive branches, e.g. after
tensor_if PASSTHROUGH/SKIP paths).
"""

from __future__ import annotations

import threading

from nnstreamer_tpu.pipeline.element import CapsEvent, Element, EosEvent, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin


@subplugin(ELEMENT, "join")
class Join(Element):
    ELEMENT_NAME = "join"
    DEVICE_PASSTHROUGH = True  # first-arrival selection, payload untouched

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_src_pad("src")
        self._push_lock = threading.Lock()

    def request_sink_pad(self):
        return self.add_sink_pad(f"sink_{len(self.sinkpads)}")

    def chain(self, pad, buf):
        with self._push_lock:  # serialize concurrent branches
            if self.srcpad.caps is None and pad.caps is not None:
                self.srcpad.set_caps(pad.caps)
            return self.srcpad.push(buf)

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            with self._push_lock:
                if self.srcpad.caps is None:
                    self.srcpad.set_caps(event.caps)
            return
        if isinstance(event, EosEvent):
            if all(p.eos for p in self.sinkpads):
                self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)
