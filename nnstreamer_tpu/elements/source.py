"""Source elements: test video, files, application push, and sensor capture.

Reference equivalents: gst core ``videotestsrc``/``filesrc``/
``multifilesrc``/``appsrc`` (used throughout the reference's SSAT pipelines)
and ``tensor_src_iio`` (``gst/nnstreamer/elements/gsttensorsrciio.c``,
2604 LoC — Linux Industrial-I/O sensor capture).
"""

from __future__ import annotations

import glob
import os
import time
from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import Fraction

_VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRA": 4, "GRAY8": 1}


@subplugin(ELEMENT, "videotestsrc")
class VideoTestSrc(SourceElement):
    """Deterministic synthetic video source (gst videotestsrc equivalent).

    Patterns: ``smpte`` (deterministic color bars), ``ball`` (moving dot,
    frame-dependent), ``gradient``, ``black``. Frames are reproducible
    functions of (pattern, frame index) so golden tests can byte-compare.
    """

    ELEMENT_NAME = "videotestsrc"
    # frames are pure functions of (pattern, frame index) and pts is
    # stamped at create() — lane workers may process them out of order
    REORDER_SAFE = True
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "num_buffers": -1,
        "pattern": "smpte",
        "width": 320,
        "height": 240,
        "format": "RGB",
        "framerate": "30/1",
        "is_live": False,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0
        self._live_t0 = None

    def start(self):
        super().start()
        # restart semantics (gst NULL→PLAYING): frame count and the
        # live-pacing epoch reset, else a stopped-and-restarted live
        # source sees a schedule T seconds in the past and floods
        self.i = 0
        self._live_t0 = None

    def _caps(self) -> Caps:
        return Caps(
            "video/x-raw",
            {
                "format": self.get_property("format"),
                "width": int(self.get_property("width")),
                "height": int(self.get_property("height")),
                "framerate": str(self.get_property("framerate")),
            },
        )

    def negotiate(self):
        self.srcpad.set_caps(self._caps())

    def _frame(self, i: int) -> np.ndarray:
        pattern = self.get_property("pattern")
        key = (pattern, self.get_property("width"),
               self.get_property("height"), self.get_property("format"))
        if pattern != "ball":
            # every pattern except ball is frame-independent: synthesize
            # once per (pattern, size, format) and reuse (buffers are
            # immutable once pushed, so the shared array is safe
            # downstream) — at high fps the per-frame synthesis otherwise
            # costs real host bandwidth. Keyed so property changes
            # invalidate the cache.
            cached_key, cached = getattr(self, "_static_frame",
                                         (None, None))
            if cached is not None and cached_key == key:
                return cached
        img = self._synthesize(i)
        if pattern != "ball":
            img.setflags(write=False)
            self._static_frame = (key, img)
        return img

    def _synthesize(self, i: int) -> np.ndarray:
        w = int(self.get_property("width"))
        h = int(self.get_property("height"))
        fmt = self.get_property("format")
        ch = _VIDEO_CHANNELS[fmt]
        pattern = self.get_property("pattern")
        if pattern == "black":
            img = np.zeros((h, w, ch), np.uint8)
        elif pattern == "gradient":
            row = np.linspace(0, 255, w, dtype=np.uint8)
            img = np.broadcast_to(row[None, :, None], (h, w, ch)).copy()
        elif pattern == "ball":
            # the one frame-dependent pattern synthesizes per frame: write
            # into a recycled aligned staging buffer (tensors/pool.py)
            # instead of allocating — the slab returns to the pool the
            # moment the last downstream reference dies
            from nnstreamer_tpu.tensors.pool import get_pool

            img = get_pool().acquire((h, w, ch), np.uint8)
            img[:] = 0
            cx = (i * 7) % w
            cy = (i * 5) % h
            y, x = np.ogrid[:h, :w]
            mask = (x - cx) ** 2 + (y - cy) ** 2 <= (min(h, w) // 8) ** 2
            img[mask] = 255
        else:  # smpte bars
            bars = np.array(
                [[255, 255, 255], [255, 255, 0], [0, 255, 255], [0, 255, 0],
                 [255, 0, 255], [255, 0, 0], [0, 0, 255]], np.uint8
            )
            idx = (np.arange(w) * 7 // max(w, 1)).clip(0, 6)
            rgb = bars[idx]
            img = np.broadcast_to(rgb[None, :, :], (h, w, 3)).copy()
            if ch == 1:
                img = img.mean(axis=2, keepdims=True).astype(np.uint8)
            elif ch == 4:
                img = np.concatenate(
                    [img, np.full((h, w, 1), 255, np.uint8)], axis=2
                )
        if img.shape[2] != ch:  # gray/alpha adjust for non-smpte patterns
            if ch == 1:
                img = img[:, :, :1]
            elif ch == 4 and img.shape[2] == 3:
                img = np.concatenate(
                    [img, np.full((h, w, 1), 255, np.uint8)], axis=2
                )
        return img

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        rate = Fraction.parse(self.get_property("framerate"))
        dur = rate.frame_duration_ns or 0
        buf = TensorBuffer([self._frame(self.i)], pts=self.i * dur,
                           duration=dur)
        if self.get_property("is_live") and dur:
            # pace against the WALL CLOCK (gst live-source semantics),
            # not sleep-per-frame: a source stalled in a downstream
            # block (e.g. the first dispatch's trace/compile) catches
            # back up to schedule instead of lagging its siblings
            # forever — which would make every slowest-sync mux row
            # wait the full stall for this pad
            if self._live_t0 is None:
                self._live_t0 = time.monotonic()
            target = self._live_t0 + (self.i + 1) * dur / 1e9
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        self.i += 1
        return buf


@subplugin(ELEMENT, "audiotestsrc")
class AudioTestSrc(SourceElement):
    """Deterministic sine-wave audio source (gst audiotestsrc equivalent)."""

    ELEMENT_NAME = "audiotestsrc"
    # each window is sample-index-addressed (phase derived from buffer
    # index), so generation order never changes the bytes
    REORDER_SAFE = True
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "num_buffers": -1,
        "samplesperbuffer": 1024,
        "freq": 440.0,
        "rate": 44100,
        "channels": 1,
        "format": "S16LE",
    }

    _DTYPES = {"S16LE": np.int16, "S8": np.int8, "F32LE": np.float32,
               "U8": np.uint8}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        self.srcpad.set_caps(Caps("audio/x-raw", {
            "format": self.get_property("format"),
            "rate": int(self.get_property("rate")),
            "channels": int(self.get_property("channels")),
        }))

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        spb = int(self.get_property("samplesperbuffer"))
        rate = int(self.get_property("rate"))
        ch = int(self.get_property("channels"))
        t0 = self.i * spb
        t = (np.arange(t0, t0 + spb) / rate)
        wave = np.sin(2 * np.pi * float(self.get_property("freq")) * t)
        dtype = self._DTYPES[self.get_property("format")]
        if np.issubdtype(dtype, np.integer):
            amp = np.iinfo(dtype).max * 0.8
            samples = (wave * amp).astype(dtype)
        else:
            samples = wave.astype(dtype)
        samples = np.repeat(samples[:, None], ch, axis=1)
        pts = int(t0 / rate * 1e9)
        self.i += 1
        return TensorBuffer([samples], pts=pts,
                            duration=int(spb / rate * 1e9))


@subplugin(ELEMENT, "filesrc")
class FileSrc(SourceElement):
    """Whole-file source (gst filesrc): one buffer of raw bytes, caps
    ``application/octet-stream`` (downstream converter interprets)."""

    ELEMENT_NAME = "filesrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "location": None,
                  "blocksize": -1}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._fh = None
        self._done = False

    def negotiate(self):
        self.srcpad.set_caps(Caps("application/octet-stream", {}))

    def create(self):
        loc = self.get_property("location")
        if loc is None or not os.path.isfile(loc):
            raise FileNotFoundError(f"filesrc: no such file {loc!r}")
        bs = int(self.get_property("blocksize"))
        if self._fh is None:
            self._fh = open(loc, "rb")
        if bs <= 0:
            if self._done:
                return None
            data = self._fh.read()
            self._done = True
        else:
            data = self._fh.read(bs)
            if not data:
                return None
        return TensorBuffer([np.frombuffer(data, np.uint8)])

    def stop(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._done = False
        super().stop()


@subplugin(ELEMENT, "multifilesrc")
class MultiFileSrc(SourceElement):
    """Sequence-of-files source (gst multifilesrc): ``location`` is a printf
    pattern (``img_%03d.raw``) or glob; one buffer per file."""

    ELEMENT_NAME = "multifilesrc"
    # one file per buffer, pts stamped with the file index at create()
    REORDER_SAFE = True
    PROPERTIES = {**SourceElement.PROPERTIES, "location": None,
                  "start_index": 0, "stop_index": -1, "caps": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = None
        self._listing = None  # cached sorted glob listing

    def negotiate(self):
        caps = self.get_property("caps")
        if isinstance(caps, str):
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            caps = parse_caps_string(caps)
        self.srcpad.set_caps(caps or Caps("application/octet-stream", {}))

    def _path(self, i: int) -> Optional[str]:
        loc = self.get_property("location")
        if "%" in loc:
            return loc % i
        if self._listing is None:
            self._listing = sorted(glob.glob(loc))  # scan once per run
        return self._listing[i] if i < len(self._listing) else None

    def create(self):
        if self.i is None:
            self.i = int(self.get_property("start_index"))
        stop = int(self.get_property("stop_index"))
        if 0 <= stop < self.i:
            return None
        path = self._path(self.i)
        if path is None or not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        buf = TensorBuffer([np.frombuffer(data, np.uint8)], pts=self.i)
        self.i += 1
        return buf

    def stop(self):
        self.i = None
        self._listing = None
        super().stop()


@subplugin(ELEMENT, "appsrc")
class AppSrc(SourceElement):
    """Application push source (gst appsrc): the app calls :meth:`push` /
    :meth:`end_of_stream`; the streaming thread forwards in order."""

    ELEMENT_NAME = "appsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "caps": None,
                  "max_buffers": 64, "block": True}

    _EOS = object()

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        import queue as _q

        self._q = _q.Queue(maxsize=int(self.get_property("max_buffers")))

    def set_caps(self, caps: Caps):
        self.set_property("caps", caps)

    def push(self, buf_or_arrays, pts: Optional[int] = None) -> bool:
        """Push a TensorBuffer (or list of arrays) into the stream.

        With ``block=false`` (gst appsrc semantics) a full queue drops the
        buffer and returns False instead of blocking the caller."""
        import queue as _q

        if not isinstance(buf_or_arrays, TensorBuffer):
            buf_or_arrays = TensorBuffer.from_arrays(buf_or_arrays, pts=pts)
        if self.get_property("block"):
            self._q.put(buf_or_arrays)
            return True
        try:
            self._q.put_nowait(buf_or_arrays)
            return True
        except _q.Full:
            return False

    def end_of_stream(self) -> None:
        self._q.put(self._EOS)

    def negotiate(self):
        caps = self.get_property("caps")
        if isinstance(caps, str):
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            caps = parse_caps_string(caps)
        if caps is not None:
            self.srcpad.set_caps(caps)

    def create(self):
        import queue as _q

        while not self._stop_evt.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except _q.Empty:
                continue
            if item is self._EOS:
                return None
            # announce caps from the first buffer if none were set
            if self.srcpad.caps is None:
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self.srcpad.set_caps(
                    TensorsConfig.from_arrays(item.tensors).to_caps()
                )
            return item
        return None


class IIOChannel:
    """One scan element: name, index and packed-sample format.

    The format descriptor mirrors the kernel's ``in_*_type`` files,
    ``[be|le]:[s|u]BITS/STORAGE>>SHIFT`` (the reference parses these in
    gsttensorsrciio.c's channel probe): STORAGE bits on the wire, BITS of
    real data after right-shifting by SHIFT, signed or unsigned.
    """

    def __init__(self, name: str, index: int, fmt: str,
                 scale: float = 1.0, offset: float = 0.0):
        self.name = name
        self.index = index
        self.scale = scale
        self.offset = offset
        try:
            endian, rest = fmt.strip().split(":")
            if endian not in ("be", "le") or rest[0] not in ("s", "u"):
                raise ValueError(f"bad endian/sign token")
            self.big_endian = endian == "be"
            self.signed = rest[0] == "s"
            bits, rest = rest[1:].split("/")
            storage, shift = (rest.split(">>") + ["0"])[:2]
            self.bits = int(bits)
            self.storage_bits = int(storage)
            self.shift = int(shift)
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"iio: malformed type descriptor {fmt!r} for channel "
                f"{name!r} (expected [be|le]:[s|u]BITS/STORAGE>>SHIFT, "
                "the kernel in_*_type format)") from e
        if self.storage_bits % 8 or self.storage_bits not in (8, 16, 32, 64):
            raise ValueError(f"iio: unsupported storage {fmt!r}")
        if not (0 < self.bits <= self.storage_bits and
                0 <= self.shift < self.storage_bits and
                self.bits + self.shift <= self.storage_bits):
            # bits/shift outside the storage word would decode silently
            # wrong (sign bit unreachable, or data shifted away)
            raise ValueError(
                f"iio: inconsistent type descriptor {fmt!r} for channel "
                f"{name!r}: BITS+SHIFT must fit in STORAGE")

    @property
    def storage_bytes(self) -> int:
        return self.storage_bits // 8

    def extract(self, raw: np.ndarray) -> np.ndarray:
        """Packed storage words → scaled float32 values."""
        dt = np.dtype(f"{'>' if self.big_endian else '<'}u"
                      f"{self.storage_bytes}")
        words = raw.view(dt).astype(np.uint64) >> np.uint64(self.shift)
        vals = words & np.uint64((1 << self.bits) - 1)
        if self.signed:
            if self.bits == 64:  # e.g. the kernel timestamp channel s64/64
                vals = vals.view(np.int64)
            else:
                # branchless sign-extend: (v XOR sign) - sign
                sign = np.int64(1) << np.int64(self.bits - 1)
                vals = (vals.astype(np.int64) ^ sign) - sign
        return ((vals.astype(np.float64) + self.offset) *
                self.scale).astype(np.float32)


@subplugin(ELEMENT, "tensor_src_iio")
class TensorSrcIIO(SourceElement):
    """Linux Industrial-I/O sensor source (reference ``tensor_src_iio``,
    gst/nnstreamer/elements/gsttensorsrciio.c, 2604 LoC).

    ``mode=device`` follows the reference's buffered-capture flow: probe
    ``<base-dir>/iio:deviceN`` sysfs (scan_elements ``in_*_{en,index,type}``
    plus per-channel scale/offset), enable channels, set
    ``sampling_frequency`` and ``buffer/length``, then read packed scans
    from ``<dev-dir>/iio:deviceN`` and demux each enabled channel by its
    type descriptor into a [channels, buffer_capacity] float32 tensor.
    ``base-dir``/``dev-dir`` default to the real kernel paths and are
    test-overridable (a mock sysfs tree replaces real hardware, the
    reference's dummy-device pattern). ``mode=mock`` needs no filesystem
    at all and synthesizes deterministic sine channels.
    """

    ELEMENT_NAME = "tensor_src_iio"
    # mock mode synthesizes index-addressed sines with pts stamped at
    # create(); device mode reads a live devnode, where the acquisition
    # snapshot depends on read timing — keep that serial
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "mode": "mock",  # "device" reads sysfs+devnode; "mock" synthesizes
        "device": None,            # device name (resolved to a number)
        "device_number": -1,
        "base_dir": "/sys/bus/iio/devices",
        "dev_dir": "/dev",
        "frequency": 100,
        "buffer_capacity": 1,
        "channels": "auto",        # "auto"|comma list of channel names
        "num_buffers": -1,
        "poll_timeout_ms": 1000,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0
        self._chans: list[IIOChannel] = []
        self._chan_offsets: list[int] = []
        self._scan_bytes = 0
        self._fh = None

    def reorder_safe(self):
        return self.get_property("mode") == "mock"

    # -- sysfs probing -------------------------------------------------------
    def _device_dir(self) -> str:
        base = self.get_property("base_dir")
        num = int(self.get_property("device_number"))
        want = self.get_property("device")
        if num < 0 and want:
            for d in sorted(glob.glob(os.path.join(base, "iio:device*"))):
                try:
                    with open(os.path.join(d, "name")) as f:
                        if f.read().strip() == want:
                            return d
                except OSError:
                    continue
            raise FileNotFoundError(f"tensor_src_iio: no device named "
                                    f"{want!r} under {base}")
        d = os.path.join(base, f"iio:device{max(num, 0)}")
        if not os.path.isdir(d):
            raise FileNotFoundError(
                f"tensor_src_iio: {d} not found (use mode=mock on hosts "
                f"without IIO hardware)")
        return d

    @staticmethod
    def _read_sysfs(path: str, default: Optional[str] = None) -> str:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            if default is None:
                raise
            return default

    @staticmethod
    def _write_sysfs(path: str, value) -> None:
        try:
            with open(path, "w") as f:
                f.write(str(value))
        except OSError:
            pass  # read-only attribute (fixed-rate sensors)

    def _probe_channels(self, dev_dir: str) -> list[IIOChannel]:
        scan = os.path.join(dev_dir, "scan_elements")
        sel = self.get_property("channels")
        # "auto" → all; an integer → first N by scan index (the element's
        # original numeric contract); otherwise a comma list of names
        wanted = None
        limit = None
        if sel not in (None, "auto"):
            if str(sel).isdigit():
                limit = int(sel)
            else:
                wanted = {c.strip() for c in str(sel).split(",")}
        probed = []
        for en_path in sorted(glob.glob(os.path.join(scan, "in_*_en"))):
            cname = os.path.basename(en_path)[len("in_"):-len("_en")]
            idx = int(self._read_sysfs(
                os.path.join(scan, f"in_{cname}_index"), "0"))
            fmt = self._read_sysfs(os.path.join(scan, f"in_{cname}_type"))
            scale = float(self._read_sysfs(
                os.path.join(dev_dir, f"in_{cname}_scale"), "1.0"))
            offset = float(self._read_sysfs(
                os.path.join(dev_dir, f"in_{cname}_offset"), "0.0"))
            probed.append((en_path, IIOChannel(cname, idx, fmt, scale,
                                               offset)))
        probed.sort(key=lambda pair: pair[1].index)
        chans = []
        for pos, (en_path, ch) in enumerate(probed):
            enable = ((wanted is None or ch.name in wanted) and
                      (limit is None or pos < limit))
            self._write_sysfs(en_path, 1 if enable else 0)
            if enable:
                chans.append(ch)
        if not chans:
            raise ValueError(f"tensor_src_iio: no scan channels enabled "
                             f"under {scan}")
        return chans

    def start(self):
        super().start()
        self.i = 0
        if self.get_property("mode") != "device":
            return
        dev_dir = self._device_dir()
        self._chans = self._probe_channels(dev_dir)
        # kernel scan layout: each element sits at an offset aligned to its
        # own storage size (index order); the whole scan pads to the widest
        # element's alignment
        off = 0
        self._chan_offsets = []
        for c in self._chans:
            sb = c.storage_bytes
            off = (off + sb - 1) // sb * sb
            self._chan_offsets.append(off)
            off += sb
        widest = max(c.storage_bytes for c in self._chans)
        self._scan_bytes = (off + widest - 1) // widest * widest
        cap = int(self.get_property("buffer_capacity"))
        self._write_sysfs(os.path.join(dev_dir, "sampling_frequency"),
                          int(self.get_property("frequency")))
        self._write_sysfs(os.path.join(dev_dir, "buffer", "length"), cap)
        self._write_sysfs(os.path.join(dev_dir, "buffer", "enable"), 1)
        node = os.path.join(self.get_property("dev_dir"),
                            os.path.basename(dev_dir))
        self._fh = open(node, "rb", buffering=0)

    def stop(self):
        # signal the streaming thread FIRST so _read_scans exits its loop
        # before the handle goes away
        self._stop_evt.set()
        if self._fh is not None:
            fh, self._fh = self._fh, None
            fh.close()
            if self.get_property("mode") == "device":
                try:
                    self._write_sysfs(
                        os.path.join(self._device_dir(), "buffer", "enable"),
                        0)
                except FileNotFoundError:
                    pass
        super().stop()

    # -- negotiation ---------------------------------------------------------
    def _num_channels(self) -> int:
        if self.get_property("mode") == "device":
            return len(self._chans)
        sel = self.get_property("channels")
        return 2 if sel in (None, "auto") else (
            int(sel) if str(sel).isdigit() else len(str(sel).split(",")))

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig, TensorsInfo

        ch = self._num_channels()
        cap = int(self.get_property("buffer_capacity"))
        info = TensorsInfo.from_str(f"{ch}:{cap}", "float32")
        cfg = TensorsConfig(
            info=info,
            rate=Fraction(int(self.get_property("frequency")), 1))
        self.srcpad.set_caps(cfg.to_caps())

    # -- capture -------------------------------------------------------------
    def _read_scans(self, cap: int) -> Optional[np.ndarray]:
        """Read ``cap`` packed scans and demux → [cap, channels] f32.

        ``poll-timeout-ms`` bounds the wait for each buffer (reference
        poll() on the char device); a quiet sensor ends the stream instead
        of hanging stop() forever.
        """
        import select

        need = self._scan_bytes * cap
        deadline = time.monotonic() + \
            max(1, int(self.get_property("poll_timeout_ms"))) / 1e3
        data = b""
        while len(data) < need and not self._stop_evt.is_set():
            fh = self._fh
            if fh is None:
                return None
            left = deadline - time.monotonic()
            if left <= 0:
                self.log.warning("poll timeout (%d bytes of %d)",
                                 len(data), need)
                return None
            try:
                ready, _, _ = select.select([fh], [], [], min(0.1, left))
            except (OSError, ValueError):
                return None  # handle closed during stop
            if not ready:
                continue
            try:
                chunk = fh.read(need - len(data))
            except (OSError, ValueError):
                return None
            if chunk is None:
                continue  # non-blocking node, nothing buffered
            if not chunk:
                return None  # EOF (mock trees use finite files)
            data += chunk
        if len(data) < need:
            return None
        raw = np.frombuffer(data, np.uint8).reshape(cap, self._scan_bytes)
        cols = []
        for c, off in zip(self._chans, self._chan_offsets):
            sl = np.ascontiguousarray(
                raw[:, off:off + c.storage_bytes]).reshape(-1)
            cols.append(c.extract(sl))
        return np.stack(cols, axis=1)

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        freq = max(1, int(self.get_property("frequency")))
        cap = int(self.get_property("buffer_capacity"))
        if self.get_property("mode") == "device":
            vals = self._read_scans(cap)
            if vals is None:
                return None
        else:
            ch = self._num_channels()
            t = self.i * cap + np.arange(cap)
            vals = np.stack(
                [np.sin(2 * np.pi * (c + 1) * t / freq) for c in range(ch)],
                axis=1,
            ).astype(np.float32)
            time.sleep(cap / freq / 100.0)  # mock pacing, 100x realtime
        buf = TensorBuffer([vals], pts=int(self.i * 1e9 / freq))
        self.i += 1
        return buf
