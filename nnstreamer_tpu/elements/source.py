"""Source elements: test video, files, application push, and sensor capture.

Reference equivalents: gst core ``videotestsrc``/``filesrc``/
``multifilesrc``/``appsrc`` (used throughout the reference's SSAT pipelines)
and ``tensor_src_iio`` (``gst/nnstreamer/elements/gsttensorsrciio.c``,
2604 LoC — Linux Industrial-I/O sensor capture).
"""

from __future__ import annotations

import glob
import os
import time
from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import Fraction

_VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRA": 4, "GRAY8": 1}


@subplugin(ELEMENT, "videotestsrc")
class VideoTestSrc(SourceElement):
    """Deterministic synthetic video source (gst videotestsrc equivalent).

    Patterns: ``smpte`` (deterministic color bars), ``ball`` (moving dot,
    frame-dependent), ``gradient``, ``black``. Frames are reproducible
    functions of (pattern, frame index) so golden tests can byte-compare.
    """

    ELEMENT_NAME = "videotestsrc"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "num_buffers": -1,
        "pattern": "smpte",
        "width": 320,
        "height": 240,
        "format": "RGB",
        "framerate": "30/1",
        "is_live": False,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def _caps(self) -> Caps:
        return Caps(
            "video/x-raw",
            {
                "format": self.get_property("format"),
                "width": int(self.get_property("width")),
                "height": int(self.get_property("height")),
                "framerate": str(self.get_property("framerate")),
            },
        )

    def negotiate(self):
        self.srcpad.set_caps(self._caps())

    def _frame(self, i: int) -> np.ndarray:
        w = int(self.get_property("width"))
        h = int(self.get_property("height"))
        fmt = self.get_property("format")
        ch = _VIDEO_CHANNELS[fmt]
        pattern = self.get_property("pattern")
        if pattern == "black":
            img = np.zeros((h, w, ch), np.uint8)
        elif pattern == "gradient":
            row = np.linspace(0, 255, w, dtype=np.uint8)
            img = np.broadcast_to(row[None, :, None], (h, w, ch)).copy()
        elif pattern == "ball":
            img = np.zeros((h, w, ch), np.uint8)
            cx = (i * 7) % w
            cy = (i * 5) % h
            y, x = np.ogrid[:h, :w]
            mask = (x - cx) ** 2 + (y - cy) ** 2 <= (min(h, w) // 8) ** 2
            img[mask] = 255
        else:  # smpte bars
            bars = np.array(
                [[255, 255, 255], [255, 255, 0], [0, 255, 255], [0, 255, 0],
                 [255, 0, 255], [255, 0, 0], [0, 0, 255]], np.uint8
            )
            idx = (np.arange(w) * 7 // max(w, 1)).clip(0, 6)
            rgb = bars[idx]
            img = np.broadcast_to(rgb[None, :, :], (h, w, 3)).copy()
            if ch == 1:
                img = img.mean(axis=2, keepdims=True).astype(np.uint8)
            elif ch == 4:
                img = np.concatenate(
                    [img, np.full((h, w, 1), 255, np.uint8)], axis=2
                )
        if img.shape[2] != ch:  # gray/alpha adjust for non-smpte patterns
            if ch == 1:
                img = img[:, :, :1]
            elif ch == 4 and img.shape[2] == 3:
                img = np.concatenate(
                    [img, np.full((h, w, 1), 255, np.uint8)], axis=2
                )
        return img

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        rate = Fraction.parse(self.get_property("framerate"))
        dur = rate.frame_duration_ns or 0
        buf = TensorBuffer([self._frame(self.i)], pts=self.i * dur,
                           duration=dur)
        if self.get_property("is_live") and dur:
            time.sleep(dur / 1e9)
        self.i += 1
        return buf


@subplugin(ELEMENT, "audiotestsrc")
class AudioTestSrc(SourceElement):
    """Deterministic sine-wave audio source (gst audiotestsrc equivalent)."""

    ELEMENT_NAME = "audiotestsrc"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "num_buffers": -1,
        "samplesperbuffer": 1024,
        "freq": 440.0,
        "rate": 44100,
        "channels": 1,
        "format": "S16LE",
    }

    _DTYPES = {"S16LE": np.int16, "S8": np.int8, "F32LE": np.float32,
               "U8": np.uint8}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        self.srcpad.set_caps(Caps("audio/x-raw", {
            "format": self.get_property("format"),
            "rate": int(self.get_property("rate")),
            "channels": int(self.get_property("channels")),
        }))

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        spb = int(self.get_property("samplesperbuffer"))
        rate = int(self.get_property("rate"))
        ch = int(self.get_property("channels"))
        t0 = self.i * spb
        t = (np.arange(t0, t0 + spb) / rate)
        wave = np.sin(2 * np.pi * float(self.get_property("freq")) * t)
        dtype = self._DTYPES[self.get_property("format")]
        if np.issubdtype(dtype, np.integer):
            amp = np.iinfo(dtype).max * 0.8
            samples = (wave * amp).astype(dtype)
        else:
            samples = wave.astype(dtype)
        samples = np.repeat(samples[:, None], ch, axis=1)
        pts = int(t0 / rate * 1e9)
        self.i += 1
        return TensorBuffer([samples], pts=pts,
                            duration=int(spb / rate * 1e9))


@subplugin(ELEMENT, "filesrc")
class FileSrc(SourceElement):
    """Whole-file source (gst filesrc): one buffer of raw bytes, caps
    ``application/octet-stream`` (downstream converter interprets)."""

    ELEMENT_NAME = "filesrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "location": None,
                  "blocksize": -1}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._fh = None
        self._done = False

    def negotiate(self):
        self.srcpad.set_caps(Caps("application/octet-stream", {}))

    def create(self):
        loc = self.get_property("location")
        if loc is None or not os.path.isfile(loc):
            raise FileNotFoundError(f"filesrc: no such file {loc!r}")
        bs = int(self.get_property("blocksize"))
        if self._fh is None:
            self._fh = open(loc, "rb")
        if bs <= 0:
            if self._done:
                return None
            data = self._fh.read()
            self._done = True
        else:
            data = self._fh.read(bs)
            if not data:
                return None
        return TensorBuffer([np.frombuffer(data, np.uint8)])

    def stop(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._done = False
        super().stop()


@subplugin(ELEMENT, "multifilesrc")
class MultiFileSrc(SourceElement):
    """Sequence-of-files source (gst multifilesrc): ``location`` is a printf
    pattern (``img_%03d.raw``) or glob; one buffer per file."""

    ELEMENT_NAME = "multifilesrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "location": None,
                  "start_index": 0, "stop_index": -1, "caps": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = None
        self._listing = None  # cached sorted glob listing

    def negotiate(self):
        caps = self.get_property("caps")
        if isinstance(caps, str):
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            caps = parse_caps_string(caps)
        self.srcpad.set_caps(caps or Caps("application/octet-stream", {}))

    def _path(self, i: int) -> Optional[str]:
        loc = self.get_property("location")
        if "%" in loc:
            return loc % i
        if self._listing is None:
            self._listing = sorted(glob.glob(loc))  # scan once per run
        return self._listing[i] if i < len(self._listing) else None

    def create(self):
        if self.i is None:
            self.i = int(self.get_property("start_index"))
        stop = int(self.get_property("stop_index"))
        if 0 <= stop < self.i:
            return None
        path = self._path(self.i)
        if path is None or not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        buf = TensorBuffer([np.frombuffer(data, np.uint8)], pts=self.i)
        self.i += 1
        return buf

    def stop(self):
        self.i = None
        self._listing = None
        super().stop()


@subplugin(ELEMENT, "appsrc")
class AppSrc(SourceElement):
    """Application push source (gst appsrc): the app calls :meth:`push` /
    :meth:`end_of_stream`; the streaming thread forwards in order."""

    ELEMENT_NAME = "appsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "caps": None,
                  "max_buffers": 64, "block": True}

    _EOS = object()

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        import queue as _q

        self._q = _q.Queue(maxsize=int(self.get_property("max_buffers")))

    def set_caps(self, caps: Caps):
        self.set_property("caps", caps)

    def push(self, buf_or_arrays, pts: Optional[int] = None) -> bool:
        """Push a TensorBuffer (or list of arrays) into the stream.

        With ``block=false`` (gst appsrc semantics) a full queue drops the
        buffer and returns False instead of blocking the caller."""
        import queue as _q

        if not isinstance(buf_or_arrays, TensorBuffer):
            buf_or_arrays = TensorBuffer.from_arrays(buf_or_arrays, pts=pts)
        if self.get_property("block"):
            self._q.put(buf_or_arrays)
            return True
        try:
            self._q.put_nowait(buf_or_arrays)
            return True
        except _q.Full:
            return False

    def end_of_stream(self) -> None:
        self._q.put(self._EOS)

    def negotiate(self):
        caps = self.get_property("caps")
        if isinstance(caps, str):
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            caps = parse_caps_string(caps)
        if caps is not None:
            self.srcpad.set_caps(caps)

    def create(self):
        import queue as _q

        while not self._stop_evt.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except _q.Empty:
                continue
            if item is self._EOS:
                return None
            # announce caps from the first buffer if none were set
            if self.srcpad.caps is None:
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self.srcpad.set_caps(
                    TensorsConfig.from_arrays(item.tensors).to_caps()
                )
            return item
        return None


@subplugin(ELEMENT, "tensor_src_iio")
class TensorSrcIIO(SourceElement):
    """Linux Industrial-I/O sensor source (reference ``tensor_src_iio``,
    gst/nnstreamer/elements/gsttensorsrciio.c:18-52).

    Reads sampled channels from ``/sys/bus/iio/devices`` + ``/dev/iio:deviceX``
    and emits ``other/tensors`` frames [channels, buffer_capacity]. On hosts
    without IIO hardware (every TPU VM), ``mode=mock`` provides a
    deterministic synthetic device so pipelines and tests still run — the
    reference's EdgeTPU ``device_type:dummy`` pattern.
    """

    ELEMENT_NAME = "tensor_src_iio"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "mode": "mock",  # "device" reads sysfs; "mock" synthesizes
        "device": None,
        "device_number": -1,
        "frequency": 100,
        "buffer_capacity": 1,
        "channels": 2,
        "num_buffers": -1,
    }

    _IIO_BASE = "/sys/bus/iio/devices"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig, TensorsInfo

        ch = int(self.get_property("channels"))
        cap = int(self.get_property("buffer_capacity"))
        info = TensorsInfo.from_str(f"{ch}:{cap}", "float32")
        cfg = TensorsConfig(info=info,
                            rate=Fraction(int(self.get_property("frequency")), 1))
        self.srcpad.set_caps(cfg.to_caps())

    def _read_device(self) -> Optional[np.ndarray]:
        num = int(self.get_property("device_number"))
        dev_dir = os.path.join(self._IIO_BASE, f"iio:device{num}")
        if not os.path.isdir(dev_dir):
            raise FileNotFoundError(
                f"tensor_src_iio: no IIO device {num} (use mode=mock on "
                f"hosts without IIO hardware)"
            )
        ch = int(self.get_property("channels"))
        cap = int(self.get_property("buffer_capacity"))
        vals = np.zeros((cap, ch), np.float32)
        in_files = sorted(glob.glob(os.path.join(dev_dir, "in_*_raw")))[:ch]
        for j in range(cap):
            for c, f in enumerate(in_files):
                with open(f) as fh:
                    vals[j, c] = float(fh.read().strip())
        return vals

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        freq = max(1, int(self.get_property("frequency")))
        if self.get_property("mode") == "device":
            vals = self._read_device()
        else:
            ch = int(self.get_property("channels"))
            cap = int(self.get_property("buffer_capacity"))
            t = self.i * cap + np.arange(cap)
            vals = np.stack(
                [np.sin(2 * np.pi * (c + 1) * t / freq) for c in range(ch)],
                axis=1,
            ).astype(np.float32)
            time.sleep(cap / freq / 100.0)  # mock pacing, 100x realtime
        buf = TensorBuffer([vals], pts=int(self.i * 1e9 / freq))
        self.i += 1
        return buf
