"""tensor_pubsub_sink / tensor_pubsub_src — buffers over pub/sub topics.

Reference: ``gst/mqtt/mqttsink.c`` / ``mqttsrc.c``: publish any stream's
buffers to a broker topic / subscribe and push them into a pipeline, with
cross-device timestamp rebasing (mqttcommon.h header + ntputil). Element
names ``mqttsink``/``mqttsrc`` are registered as aliases so reference
pipeline descriptions parse unchanged.

Two transports, selected by the ``broker`` property:

- ``shim`` (default) — the in-process framed-TCP broker
  (``query/pubsub.py``); payloads are the compact native envelope.
- ``mqtt://[host[:port]]`` — real MQTT 3.1.1 (``query/mqtt.py``);
  payloads carry the reference's 1024-byte ``GstMQTTMessageHdr``
  (caps string, num_mems/size_mems, base/sent epochs, pts/dts/duration,
  mqttcommon.h:49-63) + raw tensor memories, so streams interop with
  reference mqttsink/mqttsrc peers over any conformant broker.

Timestamp rebasing follows the reference's base-epoch math
(mqttsrc.c:1381-1404): each side stamps ``base_time_epoch`` = wall epoch
at stream start, and the receiver shifts pts by the *difference of base
epochs* — message latency never enters the offset. With ``ntp-server``
set, both sides' epochs are SNTP-corrected (``query/ntp.py``,
reference ntputil.c), so the rebasing holds across hosts whose clocks
disagree.
"""

from __future__ import annotations

import queue as _queue
import struct as _struct
import time
from typing import List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.query.pubsub import (
    Client,
    make_buffer_envelope,
    parse_buffer_envelope,
)
from nnstreamer_tpu.registry import ELEMENT, register_subplugin, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import TensorFormat, TensorsConfig


from nnstreamer_tpu.query.pubsub import parse_broker_spec as _parse_broker


def _ntp_servers(spec: Optional[str]):
    if not spec:
        return None
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        h, _, p = part.partition(":")
        out.append((h, int(p) if p else 123))
    return out or None


def _epoch(ntp_servers) -> int:
    if ntp_servers is not None:
        from nnstreamer_tpu.query.ntp import corrected_epoch_ns

        return corrected_epoch_ns(ntp_servers)
    return time.time_ns()


def _caps_to_string(caps) -> str:
    if caps is None:
        return ""
    parts = [caps.name]
    parts += [f"{k}={v}" for k, v in caps.fields.items()]
    return ",".join(parts)


class _PubSubBase:
    """Shared transport plumbing for both elements."""

    def _connect(self):
        kind, host, port = _parse_broker(
            self.get_property("broker"),
            self.get_property("host"), int(self.get_property("port")))
        self._transport = kind
        # parsed once per start — the hot path must not re-split property
        # strings per buffer
        self._ntp_list = _ntp_servers(self.get_property("ntp_server"))
        if kind == "mqtt":
            from nnstreamer_tpu.query.mqtt import MqttClient

            return MqttClient(host, port)
        return Client(host, port)

    def _epoch_now(self) -> int:
        return _epoch(self._ntp_list)


@subplugin(ELEMENT, "tensor_pubsub_sink")
class TensorPubSubSink(Element, _PubSubBase):
    ELEMENT_NAME = "tensor_pubsub_sink"
    PROPERTIES = {
        **Element.PROPERTIES,
        "host": "127.0.0.1",
        "port": 1883,
        "pub_topic": "nns/stream",
        "retain": False,
        "broker": "shim",
        "ntp_server": None,   # "host[:port][,host2...]" → SNTP-corrected
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._client = None
        self._base_epoch: Optional[int] = None

    def start(self):
        super().start()
        self._client = self._connect()
        # stream base epoch: wall clock at start (NTP-corrected when
        # configured) — the mqttsink base_time_epoch role
        self._base_epoch = self._epoch_now()

    def stop(self):
        if self._client:
            self._client.close()
            self._client = None
        super().stop()

    def _caps_str(self, pad, tensors) -> str:
        """Header caps string, cached per negotiated caps object (built
        once, not per buffer)."""
        caps = pad.caps
        if caps is None:
            caps = TensorsConfig.from_arrays(tensors).to_caps()
            return _caps_to_string(caps)
        cached = getattr(self, "_caps_str_cache", None)
        if cached is None or cached[0] is not caps:
            cached = (caps, _caps_to_string(caps))
            self._caps_str_cache = cached
        return cached[1]

    def chain(self, pad, buf):
        if self._transport == "mqtt":
            from nnstreamer_tpu.query.mqtt import pack_gst_mqtt_message

            host = buf.to_host()
            payload = pack_gst_mqtt_message(
                [np.ascontiguousarray(t).tobytes() for t in host.tensors],
                self._caps_str(pad, host.tensors),
                base_time_epoch=self._base_epoch,
                sent_time_epoch=self._epoch_now(),
                pts=buf.pts, dts=buf.dts, duration=buf.duration)
        else:
            payload = make_buffer_envelope(
                P.pack_buffer(buf), buf.pts,
                base_epoch=self._base_epoch,
                sent_epoch=self._epoch_now())
        self._client.publish(self.get_property("pub_topic"), payload,
                             retain=bool(self.get_property("retain")))
        return FlowReturn.OK


@subplugin(ELEMENT, "tensor_pubsub_src")
class TensorPubSubSrc(SourceElement, _PubSubBase):
    ELEMENT_NAME = "tensor_pubsub_src"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "host": "127.0.0.1",
        "port": 1883,
        "sub_topic": "nns/stream",
        "num_buffers": -1,
        "rebase_timestamps": True,
        "broker": "shim",
        "ntp_server": None,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client = None
        self._q: _queue.Queue = _queue.Queue(maxsize=256)
        self.i = 0
        self._base_epoch: Optional[int] = None

    def start(self):
        super().start()
        self._client = self._connect()
        self._base_epoch = self._epoch_now()
        self._client.subscribe(self.get_property("sub_topic"), self._on_msg)

    def stop(self):
        if self._client:
            self._client.close()
            self._client = None
        super().stop()

    def _on_msg(self, topic: str, body: bytes):
        try:
            self._q.put_nowait(body)
        except _queue.Full:
            pass  # drop under backpressure (mqttsrc leaky behavior)

    def negotiate(self):
        self.srcpad.set_caps(
            TensorsConfig(format=TensorFormat.FLEXIBLE).to_caps()
        )

    def _decode(self, body: bytes) -> Tuple[TensorBuffer, int,
                                            Optional[int]]:
        """wire payload → (buffer, sender base epoch, pts)."""
        if self._transport == "mqtt":
            from nnstreamer_tpu.pipeline.parse import parse_caps_string
            from nnstreamer_tpu.query.mqtt import parse_gst_mqtt_message

            msg = parse_gst_mqtt_message(body)
            tensors: List[np.ndarray] = []
            try:
                config = TensorsConfig.from_caps(
                    parse_caps_string(msg["caps_str"]))
                infos = list(config.info)
            except (ValueError, KeyError, IndexError):
                infos = []
            for i, mem in enumerate(msg["mems"]):
                if i < len(infos) and infos[i].size == len(mem):
                    tensors.append(np.frombuffer(
                        mem, infos[i].type.np_dtype
                    ).reshape(infos[i].shape))
                else:  # unknown caps: deliver raw bytes, lossless
                    tensors.append(np.frombuffer(mem, np.uint8))
            buf = TensorBuffer(tensors, dts=msg["dts"],
                               duration=msg["duration"],
                               meta={"caps_str": msg["caps_str"]})
            self._stamp_trace(buf, msg["sent_time_epoch"])
            return buf, msg["base_time_epoch"], msg["pts"]
        base_epoch, sent, pts, payload = parse_buffer_envelope(body)
        buf = P.unpack_buffer(payload)
        self._stamp_trace(buf, sent)
        return buf, base_epoch, pts

    @staticmethod
    def _stamp_trace(buf: TensorBuffer, sent_epoch_ns) -> None:
        """Both wire headers already carry a sender send-stamp (the
        reference's ``sent_time`` field / the NPE2 envelope): surface it
        as distributed-trace meta so the receiving pipeline's ledger can
        attribute the hop — no wire change, works against reference
        mqttsink peers."""
        from nnstreamer_tpu.obs import distributed as _dist

        if _dist.enabled() and sent_epoch_ns:
            buf.meta[_dist.SENT_WALL_META] = float(sent_epoch_ns) / 1e9

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        while not self._stop_evt.is_set():
            if self._client is not None and self._client.failed.is_set():
                raise RuntimeError(
                    f"{self.name}: lost broker connection "
                    f"({self.get_property('host')}:"
                    f"{self.get_property('port')})"
                )
            try:
                body = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            try:
                buf, sender_base, pts = self._decode(body)
            except (ValueError, KeyError, _struct.error) as e:
                # foreign/malformed message on a shared topic: log and keep
                # streaming (the reference mqttsrc does not die either)
                self.log.warning("dropping undecodable message (%s)", e)
                continue
            if self.get_property("rebase_timestamps") and pts is not None:
                # reference _put_timestamp_on_gst_buf: shift pts AND dts by
                # the difference of base epochs — no message latency involved
                diff = sender_base - self._base_epoch
                buf = buf.replace(
                    pts=pts + diff,
                    dts=None if buf.dts is None else buf.dts + diff)
            else:
                buf = buf.replace(pts=pts)
            self.i += 1
            return buf
        return None


# reference-name aliases so existing pipeline strings parse unchanged
register_subplugin(ELEMENT, "mqttsink", TensorPubSubSink)
register_subplugin(ELEMENT, "mqttsrc", TensorPubSubSrc)
