"""tensor_pubsub_sink / tensor_pubsub_src — buffers over pub/sub topics.

Reference: ``gst/mqtt/mqttsink.c`` / ``mqttsrc.c``: publish any stream's
buffers to a broker topic / subscribe and push them into a pipeline, with
sender-epoch timestamp rebasing (mqttcommon.h header + ntputil). Element
names ``mqttsink``/``mqttsrc`` are registered as aliases so reference
pipeline descriptions parse unchanged.
"""

from __future__ import annotations

import queue as _queue
from typing import Optional

from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.query.pubsub import (
    Client,
    make_buffer_envelope,
    parse_buffer_envelope,
)
from nnstreamer_tpu.registry import ELEMENT, register_subplugin, subplugin
from nnstreamer_tpu.tensors.types import TensorFormat, TensorsConfig


@subplugin(ELEMENT, "tensor_pubsub_sink")
class TensorPubSubSink(Element):
    ELEMENT_NAME = "tensor_pubsub_sink"
    PROPERTIES = {
        **Element.PROPERTIES,
        "host": "127.0.0.1",
        "port": 1883,
        "pub_topic": "nns/stream",
        "retain": False,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self._client: Optional[Client] = None

    def start(self):
        super().start()
        self._client = Client(self.get_property("host"),
                              int(self.get_property("port")))

    def stop(self):
        if self._client:
            self._client.close()
            self._client = None
        super().stop()

    def chain(self, pad, buf):
        payload = make_buffer_envelope(P.pack_buffer(buf), buf.pts)
        self._client.publish(self.get_property("pub_topic"), payload,
                             retain=bool(self.get_property("retain")))
        return FlowReturn.OK


@subplugin(ELEMENT, "tensor_pubsub_src")
class TensorPubSubSrc(SourceElement):
    ELEMENT_NAME = "tensor_pubsub_src"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "host": "127.0.0.1",
        "port": 1883,
        "sub_topic": "nns/stream",
        "num_buffers": -1,
        "rebase_timestamps": True,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[Client] = None
        self._q: _queue.Queue = _queue.Queue(maxsize=256)
        self.i = 0
        self._epoch_offset: Optional[int] = None

    def start(self):
        super().start()
        self._client = Client(self.get_property("host"),
                              int(self.get_property("port")))
        self._client.subscribe(self.get_property("sub_topic"), self._on_msg)

    def stop(self):
        if self._client:
            self._client.close()
            self._client = None
        super().stop()

    def _on_msg(self, topic: str, body: bytes):
        try:
            self._q.put_nowait(body)
        except _queue.Full:
            pass  # drop under backpressure (mqttsrc leaky behavior)

    def negotiate(self):
        self.srcpad.set_caps(
            TensorsConfig(format=TensorFormat.FLEXIBLE).to_caps()
        )

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        while not self._stop_evt.is_set():
            if self._client is not None and self._client.failed.is_set():
                raise RuntimeError(
                    f"{self.name}: lost broker connection "
                    f"({self.get_property('host')}:"
                    f"{self.get_property('port')})"
                )
            try:
                body = self._q.get(timeout=0.1)
            except _queue.Empty:
                continue
            sent_epoch, pts, payload = parse_buffer_envelope(body)
            buf = P.unpack_buffer(payload)
            if self.get_property("rebase_timestamps") and pts is not None:
                # rebase sender pts into this host's clock using the
                # sender-epoch delta (the reference's NTP-adjusted
                # base-time, synchronization-in-mqtt-elements.md)
                from nnstreamer_tpu.query.pubsub import epoch_ns

                if self._epoch_offset is None:
                    self._epoch_offset = epoch_ns() - sent_epoch
                buf = buf.replace(pts=pts + self._epoch_offset)
            else:
                buf = buf.replace(pts=pts)
            self.i += 1
            return buf
        return None


# reference-name aliases so existing pipeline strings parse unchanged
register_subplugin(ELEMENT, "mqttsink", TensorPubSubSink)
register_subplugin(ELEMENT, "mqttsrc", TensorPubSubSrc)
