"""tensor_quant_enc / tensor_quant_dec — int8 stream transcoding.

The dense-activation peer of the sparse pair: where ``tensor_sparse_enc``
saves bandwidth on mostly-zero tensors (reference
``gsttensorsparseenc.c``), this pair ships DENSE float tensors as
per-tensor absmax int8 (+ float32 scale) — 4× fewer bytes over
query/pubsub/gRPC transports, with ``ops/quantize.py`` providing the
device-side kernels when the payload is still in HBM.

Wire layout per tensor: TensorMetaInfo header carrying the ORIGINAL
dtype/dims (format=flexible), then u32 magic 'NQT1' (discriminates quant
blobs from other flexible payloads), float32 scale, int8[num_elements].
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.meta import HEADER_SIZE, TensorMetaInfo
from nnstreamer_tpu.tensors.types import (
    TensorFormat,
    TensorInfo,
    TensorsConfig,
)


#: discriminates quant blobs from other flexible-format payloads
_QUANT_MAGIC = b"NQT1"


def quant_encode(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(np.asarray(arr))
    xf = arr.astype(np.float32)
    scale = float(np.max(np.abs(xf))) / 127.0 if arr.size else 0.0
    scale = max(scale, 1e-30)
    q = np.clip(np.round(xf / scale), -127, 127).astype(np.int8)
    meta = TensorMetaInfo.from_info(
        TensorInfo.from_array(arr), format=TensorFormat.FLEXIBLE)
    return meta.pack() + _QUANT_MAGIC + np.float32(scale).tobytes() \
        + q.tobytes()


def quant_decode(blob: bytes, offset: int = 0):
    meta = TensorMetaInfo.unpack(blob[offset:offset + HEADER_SIZE])
    info = meta.to_info()
    p = offset + HEADER_SIZE
    if blob[p:p + 4] != _QUANT_MAGIC:
        raise ValueError("quant_decode: not a quant payload (bad magic)")
    p += 4
    need = p + 4 + info.num_elements
    if len(blob) < need:
        raise ValueError(
            f"quant_decode: truncated payload ({len(blob)} < {need} bytes)")
    scale = np.frombuffer(blob[p:p + 4], np.float32)[0]
    p += 4
    q = np.frombuffer(blob[p:p + info.num_elements], np.int8)
    p += info.num_elements
    xf = q.astype(np.float32) * scale
    dt = info.type.np_dtype
    if np.dtype(dt).kind in "iu":
        xf = np.rint(xf)  # nearest, not truncate-toward-zero
    return xf.astype(dt).reshape(info.shape), p


@subplugin(ELEMENT, "tensor_quant_enc")
class TensorQuantEnc(Element):
    ELEMENT_NAME = "tensor_quant_enc"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return TensorsConfig(format=TensorFormat.FLEXIBLE).to_caps()

    def chain(self, pad, buf):
        host = buf.to_host()  # applies any deferred finalize exactly once
        blobs = [np.frombuffer(quant_encode(t), np.uint8)
                 for t in host.tensors]
        return self.srcpad.push(host.with_tensors(blobs))


@subplugin(ELEMENT, "tensor_quant_dec")
class TensorQuantDec(Element):
    ELEMENT_NAME = "tensor_quant_dec"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def transform_caps(self, pad, caps):
        return None  # static caps derive from the first decoded frame

    def chain(self, pad, buf):
        host = buf.to_host()
        outs = []
        for t in host.tensors:
            dense, _ = quant_decode(np.ascontiguousarray(t).tobytes())
            outs.append(dense)
        if self.srcpad.caps is None:
            self.srcpad.set_caps(TensorsConfig.from_arrays(outs).to_caps())
        return self.srcpad.push(host.with_tensors(outs))
