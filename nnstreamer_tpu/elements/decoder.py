"""tensor_decoder — tensors → media, via decoder subplugins.

Reference: ``gst/nnstreamer/elements/gsttensordecoder.c`` (973 LoC) with the
subplugin API ``GstTensorDecoderDef`` (init/getOutCaps/decode,
nnstreamer_plugin_api_decoder.h:38-97). Only converter/decoder know data
semantics; a decoder turns model output tensors into labels, boxes,
keypoints, overlay video, or serialized payloads.

Subplugin protocol (duck-typed): an object (or class) with
``out_caps(config, options) -> Caps`` and
``decode(buf, config, options) -> TensorBuffer`` where ``options`` is the
dict of ``option1..optionN`` strings (reference mode options).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import DECODER, ELEMENT, get_subplugin, subplugin
from nnstreamer_tpu.tensors.types import TensorsConfig


@subplugin(ELEMENT, "tensor_decoder")
class TensorDecoder(Element):
    ELEMENT_NAME = "tensor_decoder"
    PROPERTIES = {
        **Element.PROPERTIES,
        "mode": None,
        **{f"option{i}": None for i in range(1, 10)},
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._dec = None
        self._config: Optional[TensorsConfig] = None

    def _options(self) -> Dict[str, str]:
        return {
            f"option{i}": self.get_property(f"option{i}")
            for i in range(1, 10)
            if self.get_property(f"option{i}") is not None
        }

    def _get_decoder(self):
        mode = self.get_property("mode")
        if mode is None:
            raise ValueError(f"{self.name}: mode not set")
        if self._dec is None:
            impl = get_subplugin(DECODER, mode)
            if impl is None:
                raise ValueError(f"{self.name}: no decoder subplugin {mode!r}")
            self._dec = impl() if isinstance(impl, type) else impl
        return self._dec

    def transform_caps(self, pad, caps):
        self._config = TensorsConfig.from_caps(caps)
        dec = self._get_decoder()
        return dec.out_caps(self._config, self._options())

    def device_stage(self):
        """Fuse the decoder's math into the device region when the subplugin
        splits itself: ``device_kernel(options) -> (consts, fn)`` runs on
        device inside the fused program; ``host_finalize(buf, config,
        options) -> TensorBuffer`` is deferred to the sink's materialization
        point (TensorBuffer.finalize), so the decoder never forces a blocking
        D2H mid-stream. Decoders without ``device_kernel`` stay host-side,
        exactly like reference decoders (tensordec.c decode cb is host code)."""
        dec = self._get_decoder()
        kernel = getattr(dec, "device_kernel", None)
        # both halves must exist — a kernel without its host completion
        # can't fuse (fusion is an optimization, never a failure)
        if kernel is None or getattr(dec, "host_finalize", None) is None:
            return None
        from nnstreamer_tpu.pipeline.fuse import DeviceStage

        options = self._options()
        got = kernel(options)
        if got is None:
            return None
        consts, fn = got

        def finalize(host_buf):
            return dec.host_finalize(host_buf, self._config, options)

        return DeviceStage(
            consts=consts, fn=fn,
            key=("decoder", self.get_property("mode"),
                 tuple(sorted(options.items()))),
            finalize=finalize,
        )

    def chain(self, pad, buf):
        dec = self._get_decoder()
        # materialize FIRST so the timeline's d2h span (recorded inside
        # to_host) isn't double-counted under the decode span below
        host = buf.to_host()
        tl = _timeline.ACTIVE
        if tl is None:
            out = dec.decode(host, self._config, self._options())
        else:
            t0 = time.monotonic()
            out = dec.decode(host, self._config, self._options())
            seq = buf.meta.get(_timeline.TRACE_SEQ_META)
            if seq is not None:
                tl.span("decode", seq, t0, time.monotonic(),
                        track=self.name)
        return self.srcpad.push(out)
