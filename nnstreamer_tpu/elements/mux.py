"""tensor_mux — N tensor streams → one multi-tensor frame.

Reference: ``gst/nnstreamer/elements/gsttensormux.c`` (657 LoC): collects
one buffer per sink pad (up to 16) under a sync policy and outputs a single
``other/tensors`` frame whose tensors are the concatenation of all pads'
tensors. On TPU this is the batching primitive: mux N sources, then a
``tensor_merge``/filter batches them into one XLA invoke (SURVEY §2.4.2).
"""

from __future__ import annotations

from typing import Optional

from nnstreamer_tpu.elements.collect import CollectPads
from nnstreamer_tpu.pipeline.element import (
    CapsEvent,
    Element,
    EosEvent,
    FlowReturn,
)
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import (
    NNS_TENSOR_SIZE_LIMIT,
    TensorsConfig,
    TensorsInfo,
)


@subplugin(ELEMENT, "tensor_mux")
class TensorMux(Element):
    ELEMENT_NAME = "tensor_mux"
    DEVICE_PASSTHROUGH = True  # collects/merges tensor lists by reference
    PROPERTIES = {**Element.PROPERTIES, "sync_mode": "slowest",
                  "sync_option": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_src_pad("src")
        self._collect: Optional[CollectPads] = None
        self._pad_index = {}
        self._pad_caps = {}

    def request_sink_pad(self):
        if len(self.sinkpads) >= NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(f"tensor_mux: max {NNS_TENSOR_SIZE_LIMIT} pads")
        pad = self.add_sink_pad(f"sink_{len(self.sinkpads)}")
        self._pad_index[pad] = len(self.sinkpads) - 1
        return pad

    def _get_collect(self) -> CollectPads:
        if self._collect is None:
            from nnstreamer_tpu.obs import get_registry

            hist = get_registry().histogram(
                "nns_tensor_mux_sync_wait_seconds",
                "Frame-set assembly wait under the pad-sync policy",
                **self._obs_labels())
            self._collect = CollectPads(
                num_pads=len(self.sinkpads),
                policy=self.get_property("sync_mode"),
                option=self.get_property("sync_option"),
                on_ready=self._emit,
                observe_wait=hist.observe,
            )
        return self._collect

    def chain(self, pad, buf):
        self._get_collect().push(self._pad_index[pad], buf)
        return FlowReturn.OK

    def _emit(self, frame):
        tensors = []
        pts = None
        create_ts = []
        for _, buf in frame:
            tensors.extend(buf.tensors)
            if buf.pts is not None:
                pts = max(pts, buf.pts) if pts is not None else buf.pts
            # keep every constituent frame's stamp (singular from plain
            # sources, plural from upstream aggregators/muxes)
            create_ts.extend(buf.create_stamps())
        if self.srcpad.caps is None:
            self._announce_caps(frame)
        meta = {"create_ts": create_ts} if create_ts else {}
        self.srcpad.push(TensorBuffer(tensors[:NNS_TENSOR_SIZE_LIMIT],
                                      pts=pts, meta=meta))

    def _announce_caps(self, frame):
        cfgs = []
        for i, _ in frame:
            caps = self._pad_caps.get(i)
            if caps is not None:
                cfgs.append(TensorsConfig.from_caps(caps))
        if cfgs and all(c.info.is_valid() for c in cfgs):
            infos = TensorsInfo(
                [info for c in cfgs for info in c.info.infos]
            )
            self.srcpad.set_caps(
                TensorsConfig(info=infos, rate=cfgs[0].rate).to_caps()
            )
        else:
            _, buf = frame[0]
            self.srcpad.set_caps(
                TensorsConfig.from_arrays(
                    [t for _, b in frame for t in b.tensors]
                ).to_caps()
            )

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            self._pad_caps[self._pad_index[pad]] = event.caps
            return  # output caps derived at first frame-set
        if isinstance(event, EosEvent):
            if self._collect is not None:
                all_eos = self._collect.set_eos(self._pad_index[pad])
                if all_eos:
                    for frame in self._collect.flush_remaining():
                        self._emit(frame)
                    self.srcpad.push_event(event)
            elif all(p.eos for p in self.sinkpads):
                self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)
