"""tensor_if — conditional stream branching on tensor values.

Reference: ``gst/nnstreamer/elements/gsttensorif.c`` (1161 LoC,
tensor_if/README.md): evaluates a condition on incoming tensors —
compared-value ``A_VALUE`` (scalar at an index) or ``TENSOR_AVERAGE_VALUE``,
or a registered CUSTOM callback (include/tensor_if.h) — against
``supplied-value`` with one of 10 operators, then routes the buffer
according to ``then``/``else`` actions: PASSTHROUGH, SKIP, or TENSORPICK.

Two src pads: ``src_true`` (then) and ``src_false`` (else); with
``action=SKIP`` the corresponding branch simply receives nothing.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

from nnstreamer_tpu.pipeline.element import CapsEvent, Element, EosEvent, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors import data as tdata

_custom_conds: Dict[str, Callable] = {}
_lock = threading.Lock()


def register_if_condition(name: str, fn: Callable) -> None:
    """Register a custom condition ``fn(buf) -> bool`` (reference
    nnstreamer_if_custom_register, include/tensor_if.h)."""
    with _lock:
        _custom_conds[name] = fn


_OPS = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "range_inclusive": lambda a, b: b[0] <= a <= b[1],
    "range_exclusive": lambda a, b: b[0] < a < b[1],
    "not_in_range_inclusive": lambda a, b: not (b[0] <= a <= b[1]),
    "not_in_range_exclusive": lambda a, b: not (b[0] < a < b[1]),
}


@subplugin(ELEMENT, "tensor_if")
class TensorIf(Element):
    ELEMENT_NAME = "tensor_if"
    PROPERTIES = {
        **Element.PROPERTIES,
        "compared_value": "A_VALUE",         # A_VALUE | TENSOR_AVERAGE_VALUE | CUSTOM
        "compared_value_option": "0:0:0:0,0",  # coords,tensor-idx (A_VALUE) / tensor idx / custom name
        "operator": "gt",
        "supplied_value": "0",
        "then": "PASSTHROUGH",
        "then_option": None,
        "else": "SKIP",
        "else_option": None,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src_true")
        self.add_src_pad("src_false")

    # second pad alias for parse/link ergonomics
    @property
    def src_true(self):
        return self.srcpads[0]

    @property
    def src_false(self):
        return self.srcpads[1]

    def _compared_value(self, buf) -> float:
        cv = str(self.get_property("compared_value")).upper()
        opt = str(self.get_property("compared_value_option") or "")
        if cv == "A_VALUE":
            coords_part, _, tidx_part = opt.partition(",")
            tidx = int(tidx_part) if tidx_part else 0
            arr = np.asarray(  # nns-lint: disable=NNS108 -- entry-materialized host payload (tensor_if is not DEVICE_PASSTHROUGH)
                buf.tensors[tidx])
            coords = [int(c) for c in coords_part.split(":") if c != ""]
            # coords are innermost-first dims → numpy index is reversed
            idx = tuple(reversed(coords))[-arr.ndim:] if arr.ndim else ()
            idx = tuple(0 for _ in range(arr.ndim - len(idx))) + idx
            return float(arr[idx])
        if cv == "TENSOR_AVERAGE_VALUE":
            tidx = int(opt) if opt else 0
            return tdata.average(buf.tensors[tidx])
        raise ValueError(f"tensor_if: unknown compared_value {cv!r}")

    def _supplied(self):
        sv = str(self.get_property("supplied_value"))
        if ":" in sv:
            return tuple(float(x) for x in sv.split(":")[:2])
        return float(sv)

    def _evaluate(self, buf) -> bool:
        cv = str(self.get_property("compared_value")).upper()
        if cv == "CUSTOM":
            name = str(self.get_property("compared_value_option") or "")
            with _lock:
                fn = _custom_conds.get(name)
            if fn is None:
                raise ValueError(f"tensor_if: no custom condition {name!r}")
            return bool(fn(buf))
        op = str(self.get_property("operator")).lower()
        if op not in _OPS:
            raise ValueError(f"tensor_if: unknown operator {op!r}")
        return bool(_OPS[op](self._compared_value(buf), self._supplied()))

    def _route(self, buf, branch: str):
        action = str(self.get_property(branch) or "SKIP").upper()
        pad = self.src_true if branch == "then" else self.src_false
        if action == "SKIP":
            return FlowReturn.OK
        if action == "PASSTHROUGH":
            return pad.push(buf)
        if action == "TENSORPICK":
            opt = str(self.get_property(f"{branch}_option") or "0")
            idxs = [int(i) for i in opt.split(",")]
            return pad.push(buf.with_tensors([buf.tensors[i] for i in idxs]))
        raise ValueError(f"tensor_if: unknown action {action!r}")

    def chain(self, pad, buf):
        return self._route(buf, "then" if self._evaluate(buf) else "else")

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            # both branches may get full or picked tensors; forward caps only
            # for PASSTHROUGH branches (TENSORPICK caps derive per-buffer)
            for branch, sp in (("then", self.src_true),
                               ("else", self.src_false)):
                if str(self.get_property(branch)).upper() == "PASSTHROUGH":
                    sp.set_caps(event.caps)
            return
        super().sink_event(pad, event)
