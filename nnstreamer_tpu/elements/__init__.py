"""L3 stream elements.

Importing this package registers every built-in element with the ELEMENT
registry (the reference registers its 20+ elements in one gst plugin,
``gst/nnstreamer/registerer/nnstreamer.c:85-116``)."""

from nnstreamer_tpu.pipeline.pipeline import Queue  # noqa: F401 (registers "queue")
from nnstreamer_tpu.pipeline.parse import CapsFilter  # noqa: F401 ("capsfilter")

from nnstreamer_tpu.elements import source  # noqa: F401
from nnstreamer_tpu.elements import sink  # noqa: F401
from nnstreamer_tpu.elements import converter  # noqa: F401
from nnstreamer_tpu.elements import transform  # noqa: F401
from nnstreamer_tpu.elements import filter as filter_element  # noqa: F401
from nnstreamer_tpu.elements import decoder  # noqa: F401
from nnstreamer_tpu.elements import mux  # noqa: F401
from nnstreamer_tpu.elements import demux  # noqa: F401
from nnstreamer_tpu.elements import merge  # noqa: F401
from nnstreamer_tpu.elements import split  # noqa: F401
from nnstreamer_tpu.elements import join  # noqa: F401
from nnstreamer_tpu.elements import tee  # noqa: F401
from nnstreamer_tpu.elements import aggregator  # noqa: F401
from nnstreamer_tpu.elements import rate  # noqa: F401
from nnstreamer_tpu.elements import cond  # noqa: F401
from nnstreamer_tpu.elements import crop  # noqa: F401
from nnstreamer_tpu.elements import repo  # noqa: F401
from nnstreamer_tpu.elements import sparse  # noqa: F401
from nnstreamer_tpu.elements import quant  # noqa: F401
from nnstreamer_tpu.elements import query  # noqa: F401
from nnstreamer_tpu.elements import lm_serve  # noqa: F401
from nnstreamer_tpu.elements import pubsub  # noqa: F401

from nnstreamer_tpu.elements import grpc_io  # noqa: F401 (grpcio itself
# is imported lazily inside the elements' start())
