"""tensor_crop — crop raw-tensor regions using a second "info" pad.

Reference: ``gst/nnstreamer/elements/gsttensorcrop.c`` (820 LoC,
tensor_crop.c:20-36): the ``raw`` sink pad carries data tensors, the
``info`` sink pad carries crop coordinates (x, y, w, h per region, e.g.
from a detection model); output is a flexible-format stream of cropped
regions (shapes vary per frame).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from nnstreamer_tpu.elements.collect import CollectPads
from nnstreamer_tpu.pipeline.element import CapsEvent, Element, EosEvent, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import TensorFormat, TensorsConfig


@subplugin(ELEMENT, "tensor_crop")
class TensorCrop(Element):
    ELEMENT_NAME = "tensor_crop"
    PROPERTIES = {**Element.PROPERTIES, "lateness": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.raw_pad = self.add_sink_pad("raw")
        self.info_pad = self.add_sink_pad("info")
        self.add_src_pad("src")
        self._collect = CollectPads(num_pads=2, policy="slowest",
                                    on_ready=self._emit)

    def chain(self, pad, buf):
        self._collect.push(0 if pad is self.raw_pad else 1, buf)
        return FlowReturn.OK

    def _emit(self, frame):
        by_pad = dict(frame)
        raw, info = by_pad.get(0), by_pad.get(1)
        if raw is None or info is None:
            return
        data = np.asarray(raw.tensors[0])
        if data.ndim == 4 and data.shape[0] == 1:
            data = data[0]  # (H, W, C)
        regions = np.asarray(info.tensors[0]).reshape(-1, 4).astype(int)
        crops = []
        for x, y, w, h in regions:
            x0, y0 = max(0, x), max(0, y)
            crop = data[y0:y0 + h, x0:x0 + w]
            crops.append(np.ascontiguousarray(crop))
        if self.srcpad.caps is None:
            cfg = TensorsConfig(format=TensorFormat.FLEXIBLE)
            self.srcpad.set_caps(cfg.to_caps())
        self.srcpad.push(raw.with_tensors(crops).replace(
            meta={**raw.meta, "crop_regions": regions.tolist()}
        ))

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            return
        if isinstance(event, EosEvent):
            if self._collect.set_eos(0 if pad is self.raw_pad else 1):
                self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)
