"""tensor_crop — crop raw-tensor regions using a second "info" pad.

Reference: ``gst/nnstreamer/tensor_crop/tensor_crop.c`` (820 LoC): the
``raw`` sink pad carries data tensors, the ``info`` pad carries crop
coordinates (x, y, w, h per region, e.g. from a detection model); output
is a flexible-format stream of cropped regions (shapes vary per frame).

Parity points:

- **every data tensor is cropped** per region (multi-tensor raw frames;
  output is region-major: all tensors of region 0, then region 1, ...).
- ``lateness`` (ms, default -1 = disabled, tensor_crop.c:734-759): when
  raw and info timestamps differ by more than this, the older buffer is
  dropped and the newer kept for the next pairing.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.elements.collect import CollectPads
from nnstreamer_tpu.pipeline.element import CapsEvent, Element, EosEvent, FlowReturn
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.types import TensorFormat, TensorsConfig


@subplugin(ELEMENT, "tensor_crop")
class TensorCrop(Element):
    ELEMENT_NAME = "tensor_crop"
    PROPERTIES = {**Element.PROPERTIES, "lateness": -1}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.raw_pad = self.add_sink_pad("raw")
        self.info_pad = self.add_sink_pad("info")
        self.add_src_pad("src")
        self._collect = CollectPads(num_pads=2, policy="slowest",
                                    on_ready=self._emit)

    def chain(self, pad, buf):
        self._collect.push(0 if pad is self.raw_pad else 1, buf)
        return FlowReturn.OK

    def _late(self, raw, info) -> bool:
        """Reject the pairing when timestamps diverge beyond ``lateness``
        (tensor_crop.c:734-759: drop the older, keep the newer)."""
        lateness_ms = int(self.get_property("lateness"))
        if lateness_ms < 0 or raw.pts is None or info.pts is None:
            return False
        if abs(raw.pts - info.pts) <= lateness_ms * 1_000_000:
            return False
        if raw.pts > info.pts:
            self._collect.requeue_front(0, raw)   # info was old: drop it
        else:
            self._collect.requeue_front(1, info)  # raw was old: drop it
        self.log.debug("lateness: dropped old buffer (raw pts %s, info "
                       "pts %s)", raw.pts, info.pts)
        # the kept buffer may already have a partner queued — pair it now
        # rather than waiting for (possibly never-coming) next arrival
        self._collect.recheck()
        return True

    def _emit(self, frame):
        by_pad = dict(frame)
        raw, info = by_pad.get(0), by_pad.get(1)
        if raw is None or info is None:
            return
        if self._late(raw, info):
            return
        datas = []
        for t in raw.tensors:
            data = np.asarray(t)
            if data.ndim == 4 and data.shape[0] == 1:
                data = data[0]  # (H, W, C)
            datas.append(data)
        regions = np.asarray(  # nns-lint: disable=NNS108 -- entry-materialized host payload (tensor_crop is not DEVICE_PASSTHROUGH)
            info.tensors[0]).reshape(-1, 4).astype(int)
        crops = []
        # region-major: all data tensors cropped at region 0, then 1, ...
        for x, y, w, h in regions:
            x0, y0 = max(0, x), max(0, y)
            for data in datas:
                crop = data[y0:y0 + h, x0:x0 + w]
                crops.append(np.ascontiguousarray(crop))
        if self.srcpad.caps is None:
            cfg = TensorsConfig(format=TensorFormat.FLEXIBLE)
            self.srcpad.set_caps(cfg.to_caps())
        self.srcpad.push(raw.with_tensors(crops).replace(
            meta={**raw.meta, "crop_regions": regions.tolist(),
                  "crop_num_tensors": len(datas)}
        ))

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            return
        if isinstance(event, EosEvent):
            all_eos = self._collect.set_eos(0 if pad is self.raw_pad else 1)
            if all_eos:
                self._collect.recheck()  # emit any ready leftover pairing
                self.srcpad.push_event(event)
            return
        super().sink_event(pad, event)
