"""tensor_query_client / tensor_query_serversrc / tensor_query_serversink —
distributed pipeline offload elements.

Reference: ``gst/nnstreamer/tensor_query/`` — the client sends each input
buffer to a remote server pipeline and pushes the returned result
downstream (tensor_query_client.c:609); the server pipeline is bracketed by
serversrc (receives client buffers) and serversink (routes each result back
to its client by client-id meta). Client failover walks a server list
(``_client_retry_connection``:465; hybrid/MQTT discovery provides the list
— see ``query.discovery``).
"""

from __future__ import annotations

import os
import select
import socket
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.obs import distributed as _dist
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.query import balance as _bal
from nnstreamer_tpu.pipeline.element import (
    CapsEvent,
    Element,
    FlowError,
    FlowReturn,
)
from nnstreamer_tpu.pipeline.pipeline import SourceElement
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.query import resilience as _res
from nnstreamer_tpu.query.server import QueryServer
from nnstreamer_tpu.registry import ELEMENT, subplugin
from nnstreamer_tpu.tensors.types import TensorFormat, TensorsConfig


class _BChannel:
    """One balance-mode connection to one replica endpoint: its socket,
    its dt1 grant, and the entries currently routed to it (send order).

    Reconnects are sticky: a failed channel retries ITS endpoint with
    bounded backoff before its entries are rerouted, so resends land in
    that replica's (possibly checkpoint-restored) dedup window and stay
    exactly-once across a rolling restart; only after ``max_retry``
    consecutive failures do the survivors hedge to a sibling replica."""

    __slots__ = ("endpoint", "sock", "dt1", "pending", "failures",
                 "next_attempt_t")

    def __init__(self, endpoint: Tuple[str, int]):
        self.endpoint = endpoint
        self.sock: Optional[socket.socket] = None
        self.dt1 = False
        self.pending: List[_res.PendingEntry] = []
        self.failures = 0          # consecutive connect/stall failures
        self.next_attempt_t = 0.0  # monotonic gate on the next reconnect

    def kill(self) -> None:
        sock, self.sock = self.sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


@subplugin(ELEMENT, "tensor_query_client")
class TensorQueryClient(Element):
    ELEMENT_NAME = "tensor_query_client"
    PROPERTIES = {
        **Element.PROPERTIES,
        "host": "127.0.0.1",
        "port": 3000,
        "dest_host": None,   # alias pair (reference uses dest-host/dest-port)
        "dest_port": None,
        "servers": None,     # failover list "host1:port1,host2:port2"
        "timeout": P.DEFAULT_TIMEOUT,
        "max_retry": 3,
        # >1 pipelines the offload: up to N requests ride the connection
        # before the first result is awaited (responses return in order).
        # Hides the network+invoke round trip behind the stream — essential
        # when the server's accelerator has dispatch latency. 1 = the
        # reference's synchronous per-frame round trip (with per-frame
        # resend-on-reconnect); >1 drops in-flight frames on a connection
        # error (streaming frame-drop semantics, tensor_filter.c:699-705).
        "max_in_flight": 1,
        # broker discovery (reference query-hybrid): find servers by
        # operation name instead of static host/port
        "operation": None,
        "broker_host": "127.0.0.1",
        "broker_port": 1883,
        # read-only counter: frames lost to connection failures while in
        # flight (max_in_flight>1). Lets callers detect lossy runs without
        # log scraping — a flaky link can otherwise drop a large fraction
        # of the stream while still ending in a clean EOS.
        "frames_dropped": 0,
        # "nnstpu" = NTQ1 framing; "nnstreamer" = the reference's
        # raw-struct wire (query/refwire.py) — offload to an UNMODIFIED
        # reference tensor_query_serversrc/serversink pair
        "wire": "nnstpu",
        # refwire result connection (reference server-sink port);
        # 0 → src port + 1 (the reference's usual pairing)
        "sink_port": 0,
        # -- resilient transport (query/resilience.py) -------------------
        # All off by default: with none of these set the classic wire
        # (commands 1-8) and the classic frame-drop semantics above are
        # byte-identical to pre-resilience builds.
        # reliable=true switches to the extended protocol: per-request
        # ids + a server dedup window make reconnect resends idempotent,
        # so in-flight frames (any max-in-flight) are resent in order
        # after a connection error instead of dropped. Requires a
        # serversrc started with reliable=true (nnstpu wire only).
        "reliable": False,
        # forward the frame's remaining SLO slack (meta deadline_t, as
        # stamped by a local slo-budget queue) in the TRANSFER_EX header
        # so the REMOTE scheduler sheds work that can no longer make its
        # budget; shed/late frames come back as EXPIRED, not results
        "propagate_deadline": False,
        # per-endpoint circuit breaker: open after N consecutive connect
        # failures, re-probe (half-open) after breaker-reset-ms
        "breaker_failures": 5,
        "breaker_reset_ms": 1000.0,
        # >0 arms hedged failover: when no result lands within
        # max(hedge-ms, p99 * 1.5) the client fails over to the next
        # replica and resends (the dedup window absorbs duplicates)
        "hedge_ms": 0.0,
        # reconnect backoff base (bounded exponential, jittered)
        "reconnect_backoff_ms": 50.0,
        # read-only counter: frames the REMOTE end expired (deadline
        # propagation) — intentional sheds, not losses
        "frames_expired": 0,
        # -- fleet balancing (query/balance.py) --------------------------
        # "shortest-slack" (requires reliable=true) keeps a channel per
        # live endpoint and routes each frame to the one with the lowest
        # expected completion time (per-endpoint RTT EWMA + local
        # in-flight + the load block of refreshed discovery ads).
        # Results deliver downstream in send order. "off" (default, also
        # forced by NNSTPU_FLEET=0) keeps the single-connection path
        # byte-identical to pre-fleet builds.
        "balance": "off",
        # >0 ages discovery ads out of the balancer's candidate list
        # when a replica stops refreshing (pair with the serversrc's
        # advertise-interval-s; 0 trusts retained ads forever)
        "discovery_stale_s": 0.0,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self._sock = None
        self._refclient = None      # refwire transport when wire=nnstreamer
        self._server_config = None  # refwire: server caps → TensorsConfig
        self._client_id = None
        self._server_idx = 0
        self._lock = threading.Lock()
        #: (pts, meta) of requests sent but not yet answered (in order)
        self._pending: List[tuple] = []
        # -- reliable-mode state (query/resilience.py) -------------------
        #: stable identity across reconnects — the server's dedup window
        #: and result routing key on this, not on the per-connection id
        self._r_instance = uuid.uuid4().hex
        self._r_next_id = 1  # monotone per-instance request id
        self._r_pending: List[_res.PendingEntry] = []
        self._r_breakers: dict = {}  # (host, port) → CircuitBreaker
        #: (host, port) → EndpointStats — per-endpoint like the breakers,
        #: so hedge timeouts and balancer scores use the latency
        #: distribution of the replica actually being talked to
        self._r_stats: Dict[Tuple[str, int], _res.EndpointStats] = {}
        self._r_endpoint: Optional[Tuple[str, int]] = None
        #: this connection granted the dt1 distributed-trace feature in
        #: its HELLO echo — only then do we speak TRANSFER_EX2
        self._r_dt1 = False
        # -- balance-mode state (query/balance.py) -----------------------
        self._b_channels: Dict[Tuple[str, int], _BChannel] = {}
        self._b_pending: Dict[int, _res.PendingEntry] = {}  # req_id →
        self._b_results: Dict[int, tuple] = {}  # req_id → (result, entry)
        self._b_done_ids: set = set()  # completed without a result
        self._b_deliver_next: Optional[int] = None  # in-order watermark
        self._b_discovery = None  # persistent ServerDiscovery (balance)

    def set_property(self, key: str, value) -> None:
        if key.replace("-", "_") in ("frames_dropped", "frames_expired"):
            raise ValueError(f"tensor_query_client: {key} is read-only")
        super().set_property(key, value)

    def _drop_pending_locked(self) -> int:
        """Clear in-flight requests, bumping the frames-dropped counter."""
        n = len(self._pending) + len(self._r_pending) + len(self._b_pending)
        if n:
            self._pending.clear()
            self._r_pending.clear()
            self._b_pending.clear()
            self._props["frames_dropped"] = \
                int(self._props.get("frames_dropped", 0)) + n
        for ch in self._b_channels.values():
            ch.pending.clear()
        self._b_results.clear()
        self._b_done_ids.clear()
        self._b_deliver_next = None
        return n

    def _server_list(self) -> List[Tuple[str, int]]:
        operation = self.get_property("operation")
        if operation:
            from nnstreamer_tpu.query.discovery import ServerDiscovery

            disco = ServerDiscovery(self.get_property("broker_host"),
                                    int(self.get_property("broker_port")),
                                    str(operation))
            try:
                found = disco.wait_servers(
                    timeout=float(self.get_property("timeout")))
            finally:
                disco.close()
            if not found:
                raise P.QueryProtocolError(
                    f"no servers advertise operation {operation!r}"
                )
            return found
        servers = self.get_property("servers")
        if servers:
            out = []
            for item in str(servers).split(","):
                h, p = item.rsplit(":", 1)
                out.append((h.strip(), int(p)))
            return out
        host = self.get_property("dest_host") or self.get_property("host")
        port = int(self.get_property("dest_port") or self.get_property("port"))
        return [(host, port)]

    def _refwire(self) -> bool:
        return str(self.get_property("wire")) == "nnstreamer"

    def _connect_one(self, host: str, port: int) -> None:
        """One connection attempt on the configured wire."""
        timeout = float(self.get_property("timeout"))
        if self._refwire():
            from nnstreamer_tpu.query import refwire as R

            # gst-style caps text — what a real reference server parses
            in_caps = (self.sinkpad.caps.to_string()
                       if self.sinkpad.caps else "")
            sink_port = int(self.get_property("sink_port") or 0) or None
            rc = R.RefWireClient(host, port, sink_port=sink_port,
                                 in_caps=in_caps, timeout=timeout)
            self._refclient = rc
            self._client_id = rc.client_id
            self._server_config = None
            if rc.server_caps:
                try:
                    from nnstreamer_tpu.pipeline.parse import (
                        parse_caps_string,
                    )

                    self._server_config = TensorsConfig.from_caps(
                        parse_caps_string(rc.server_caps))
                except Exception:  # noqa: BLE001 — results stay u8
                    self.log.info("server caps %r not parseable; "
                                  "results surface as u8",
                                  rc.server_caps)
            self._sock = rc  # truthy connection marker for chain()
            return
        sock, cid = self._open_nnstpu(host, port)
        if cid is not None:
            self._client_id = cid
        self._sock = sock

    def _open_nnstpu(self, host: str,
                     port: int) -> Tuple[socket.socket, Optional[int]]:
        """Classic-wire connect + handshake, returning the fresh socket
        and the server-assigned client id (balance mode opens one of
        these per endpoint; the single-connection paths assign it to
        ``self._sock``)."""
        caps_repr = repr(self.sinkpad.caps) if self.sinkpad.caps else ""
        timeout = float(self.get_property("timeout"))
        sock = P.connect(host, port, timeout=timeout)
        P.send_msg(sock, P.Cmd.REQUEST_INFO, caps_repr.encode())
        cmd, payload = P.recv_msg(sock)
        if cmd is P.Cmd.DENY:
            raise P.QueryProtocolError(f"server {host}:{port} denied")
        if cmd is not P.Cmd.APPROVE:
            raise P.QueryProtocolError(f"bad handshake reply {cmd}")
        cmd, payload = P.recv_msg(sock)
        cid = int(payload.decode()) if cmd is P.Cmd.CLIENT_ID else None
        return sock, cid

    def _connect(self):
        """Connect with failover across the server list (reference
        _client_retry_connection)."""
        servers = self._server_list()
        last_err = None
        for attempt in range(int(self.get_property("max_retry")) *
                             len(servers)):
            host, port = servers[self._server_idx % len(servers)]
            try:
                self._connect_one(host, port)
                return
            except (OSError, P.QueryProtocolError) as e:
                last_err = e
                self._server_idx += 1
                self.log.warning("connect to %s:%d failed (%s); trying next",
                                 host, port, e)
        raise P.QueryProtocolError(
            f"all query servers unreachable: {last_err}"
        )

    def stop(self):
        with self._lock:
            if self._refclient is not None:
                self._refclient.close()
                self._refclient = None
                self._sock = None
            elif self._sock is not None:
                try:
                    P.send_msg(self._sock, P.Cmd.BYE)
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            for ch in self._b_channels.values():
                if ch.sock is not None:
                    try:
                        P.send_msg(ch.sock, P.Cmd.BYE)
                    except OSError:
                        pass
                ch.kill()
            self._b_channels.clear()
            if self._b_discovery is not None:
                self._b_discovery.close()
                self._b_discovery = None
            # in-flight requests die with the connection — a restart must
            # not pair old (pts, meta) with new results
            self._drop_pending_locked()
        super().stop()

    def transform_caps(self, pad, caps):
        return None  # output caps come from the first result buffer

    def _send_buf(self, buf):
        act = None
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("query.send",
                            seq=buf.meta.get(_timeline.TRACE_SEQ_META))
            if act == "drop":
                return  # the bytes vanish; recv timeout / retry recovers
            if act == "disconnect":
                self._kill_sock()
                raise OSError("injected fault: query.send disconnect")
        if self._refclient is not None:
            from nnstreamer_tpu.query import refwire as R

            if act == "corrupt":  # refwire has no framed payload to
                # mangle in place — the nearest physical fault is a
                # connection killed mid-send
                self._kill_sock()
                raise OSError("injected fault: query.send corrupt")
            self._refclient.send(R.buffer_to_mems(buf.to_host()),
                                 pts=buf.pts)
        else:
            payload = P.pack_buffer(buf)
            if act == "corrupt":
                # truncation is guaranteed-detectable: the server's
                # unpack runs out of bytes and kicks this connection
                payload = payload[:max(1, len(payload) // 2)]
            P.send_msg(self._sock, P.Cmd.TRANSFER, payload)

    def _disconnect_locked(self):
        if self._refclient is not None:
            self._refclient.close()
            self._refclient = None
        self._sock = None

    def _kill_sock(self):
        """Close and forget the current connection (both wires)."""
        sock = self._sock
        self._sock = None
        if self._refclient is not None:
            try:
                self._refclient.close()
            except OSError:
                pass
            self._refclient = None
        elif sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _recv_result(self):
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("query.recv")
            if act == "disconnect":
                self._kill_sock()
                raise OSError("injected fault: query.recv disconnect")
            if act is not None:
                # drop/corrupt of an in-order result poisons the
                # response stream — surface as a protocol error so the
                # caller's reconnect logic takes over
                raise P.QueryProtocolError(
                    f"injected fault: query.recv {act}")
        if self._refclient is not None:
            from nnstreamer_tpu.query import refwire as R
            from nnstreamer_tpu.tensors.buffer import TensorBuffer

            info, mems = self._refclient.recv_result()
            if self._server_config is not None:
                return R.mems_to_buffer(mems, self._server_config, info)
            import numpy as np

            return TensorBuffer(
                [np.frombuffer(m, dtype=np.uint8) for m in mems],
                pts=info.get("pts"))
        cmd, payload = P.recv_msg(self._sock)
        if cmd is not P.Cmd.RESULT:
            raise P.QueryProtocolError(f"expected RESULT, got {cmd}")
        return P.unpack_buffer(payload)

    def _push_result(self, result, pts, meta):
        result = result.replace(pts=pts, meta=dict(meta))
        if self.srcpad.caps is None:
            self.srcpad.set_caps(
                TensorsConfig.from_arrays(result.tensors).to_caps()
            )
        return self.srcpad.push(result)

    # -- reliable transport (query/resilience.py) ---------------------------
    def _r_breaker(self, host: str, port: int) -> _res.CircuitBreaker:
        key = (host, port)
        br = self._r_breakers.get(key)
        if br is None:
            br = self._r_breakers[key] = _res.CircuitBreaker(
                failures=int(self.get_property("breaker_failures") or 5),
                reset_s=float(self.get_property("breaker_reset_ms")
                              or 1000.0) / 1e3,
                endpoint=f"{host}:{port}")
        return br

    def _r_stat(self, host: str, port: int) -> _res.EndpointStats:
        key = (host, port)
        st = self._r_stats.get(key)
        if st is None:
            st = self._r_stats[key] = _res.EndpointStats()
        return st

    def _r_make_entry(self, buf) -> _res.PendingEntry:
        deadline_t = None
        if self.get_property("propagate_deadline"):
            d = buf.meta.get("deadline_t")
            if d is not None:
                deadline_t = float(d)
        req_id = self._r_next_id
        self._r_next_id += 1
        return _res.PendingEntry(req_id, buf.pts, dict(buf.meta),
                                 P.pack_buffer(buf), deadline_t=deadline_t)

    def _r_send_entry(self, entry: _res.PendingEntry,
                      ch: Optional["_BChannel"] = None) -> None:
        """Send (or resend) one entry as TRANSFER_EX. The slack is
        recomputed from the entry's deadline at every send, so a resend
        carries the budget that is actually left. ``ch`` routes the send
        over a balance-mode channel; None (default) is the classic
        single-connection path, byte-identical to pre-fleet builds."""
        sock = self._sock if ch is None else ch.sock
        dt1 = self._r_dt1 if ch is None else ch.dt1
        entry.endpoint = self._r_endpoint if ch is None else ch.endpoint
        now = time.monotonic()
        if dt1:
            trace_id = entry.meta.get(_timeline.TRACE_SEQ_META)
            entry.sent_wall = _dist.wall_now()
            cmd = P.Cmd.TRANSFER_EX2
            payload = P.pack_ext2(
                entry.req_id, entry.slack_s(now),
                int(trace_id) if trace_id is not None else entry.req_id,
                entry.sent_wall, b"", entry.body)
        else:
            cmd = P.Cmd.TRANSFER_EX
            payload = P.pack_ext(entry.req_id, entry.slack_s(now),
                                 entry.body)
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("query.send",
                            seq=entry.meta.get(_timeline.TRACE_SEQ_META))
            if act == "drop":
                entry.sent_t = now
                return  # swallowed; the recv timeout path resends it
            if act == "disconnect":
                if ch is None:
                    self._kill_sock()
                else:
                    ch.kill()
                raise OSError("injected fault: query.send disconnect")
            if act == "corrupt":
                # guaranteed-detectable: the server's unpack runs out of
                # bytes, forgets the dedup entry, and kicks us — the
                # resend after reconnect re-invokes exactly once
                payload = payload[:max(1, len(payload) // 2)]
        P.send_msg(sock, cmd, payload)
        entry.sent_t = now

    def _hello_on(self, sock) -> bool:
        """HELLO handshake on one connection; returns the dt1 grant."""
        window = max(1, int(self.get_property("max_in_flight")))
        P.send_msg(sock, P.Cmd.HELLO,
                   f"{self._r_instance}:{max(64, window * 8)}"
                   f"{_dist.hello_offer()}".encode())
        try:
            cmd, payload = P.recv_msg(sock)
        except socket.timeout:
            raise P.QueryProtocolError(
                "server did not acknowledge HELLO — reliable mode needs "
                "a tensor_query_serversrc started with reliable=true"
            ) from None
        if cmd is not P.Cmd.HELLO:
            raise P.QueryProtocolError(
                f"bad HELLO reply {cmd} — reliable mode needs a "
                f"tensor_query_serversrc started with reliable=true")
        return _dist.hello_accepts(payload)

    def _r_hello(self) -> None:
        self._r_dt1 = False
        self._r_dt1 = self._hello_on(self._sock)

    def _r_resend_pending(self) -> None:
        """Resend the undelivered suffix in order after a reconnect.
        Everything still pending is resent — the server's dedup window
        replays results for frames that DID land, so over-resending is
        safe and under-resending (the real loss bug) is impossible."""
        if not self._r_pending:
            return
        m = _res.metrics()
        tl = _timeline.ACTIVE
        for entry in self._r_pending:
            self._r_send_entry(entry)
            m["retries"].inc()
            if tl is not None:
                tl.mark("net_retry",
                        entry.meta.get(_timeline.TRACE_SEQ_META),
                        track="net", req_id=entry.req_id)
        self.log.info("resent %d in-flight frame(s) after reconnect",
                      len(self._r_pending))

    def _r_ensure_connected(self) -> None:
        """Reconnect with per-endpoint circuit breaking and bounded
        jittered backoff, then handshake (classic + HELLO) and resend
        the undelivered suffix."""
        if self._sock is not None:
            return
        servers = self._server_list()
        policy = _res.RetryPolicy(
            base_ms=float(self.get_property("reconnect_backoff_ms")
                          or 50.0),
            key=self.name)
        last_err: Optional[Exception] = None
        attempts = max(1, int(self.get_property("max_retry"))) * \
            len(servers)
        for attempt in range(1, attempts + 1):
            host, port = servers[self._server_idx % len(servers)]
            breaker = self._r_breaker(host, port)
            if not breaker.allow():
                if last_err is None:
                    last_err = P.QueryProtocolError(
                        f"breaker open for {host}:{port}")
                self._server_idx += 1
                policy.sleep(attempt)
                continue
            try:
                self._connect_one(host, port)
                # stamped before the resends so every entry's RTT
                # observation credits the endpoint it was sent to
                self._r_endpoint = (host, port)
                self._r_hello()
                self._r_resend_pending()
            except (OSError, P.QueryProtocolError) as e:
                last_err = e
                breaker.record_failure()
                self._kill_sock()
                self._server_idx += 1
                self.log.warning("connect to %s:%d failed (%s); "
                                 "backing off", host, port, e)
                policy.sleep(attempt)
                continue
            breaker.record_success()
            return
        raise P.QueryProtocolError(
            f"all query servers unreachable: {last_err}")

    def _r_conn_failure(self, err: Exception) -> None:
        if self._r_endpoint is not None:
            self._r_breaker(*self._r_endpoint).record_failure()
        self._kill_sock()
        self.log.warning("reliable transport error: %s; will reconnect",
                         err)

    def _r_transmit(self, entry: _res.PendingEntry) -> None:
        """Send a new entry, reconnecting through failures. Once the
        entry is in ``_r_pending`` the resend-on-reconnect discipline
        owns it; this loop only has to get the FIRST copy out."""
        failures = 0
        while True:
            self._r_ensure_connected()
            try:
                self._r_send_entry(entry)
                self._r_pending.append(entry)
                return
            except (OSError, P.QueryProtocolError) as e:
                failures += 1
                self._r_conn_failure(e)
                if failures > max(1, int(self.get_property("max_retry"))):
                    raise

    def _r_recv(self, timeout: float):
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("query.recv")
            if act == "disconnect":
                self._kill_sock()
                raise OSError("injected fault: query.recv disconnect")
            if act is not None:
                raise P.QueryProtocolError(
                    f"injected fault: query.recv {act}")
        self._sock.settimeout(max(0.001, timeout))
        return P.recv_msg(self._sock)

    def _r_pop_pending(self, req_id: int) -> Optional[_res.PendingEntry]:
        for i, entry in enumerate(self._r_pending):
            if entry.req_id == req_id:
                return self._r_pending.pop(i)
        return None

    def _r_drain_locked(self, min_pending: int):
        """Receive until fewer than ``min_pending`` entries remain in
        flight (caller holds the lock). Returns ``(done, err)`` where
        ``done`` is ``[(result, entry), ...]`` in arrival order; a recv
        timeout hedges to the next replica (when armed) or reconnects,
        and only after ``max_retry`` consecutive recoveries without
        progress does ``err`` report the failure (with the still-pending
        frames dropped and counted — the honest last resort)."""
        done: List[tuple] = []
        err: Optional[Exception] = None
        failures = 0
        limit = max(1, int(self.get_property("max_retry")))
        timeout = float(self.get_property("timeout"))
        hedge_ms = float(self.get_property("hedge_ms") or 0.0)
        tl = _timeline.ACTIVE
        while len(self._r_pending) >= min_pending:
            hedging = hedge_ms > 0.0 and failures == 0
            if hedging:
                st = self._r_stat(*self._r_endpoint) \
                    if self._r_endpoint is not None else None
                recv_t = min(timeout,
                             st.hedge_timeout(hedge_ms / 1e3)
                             if st is not None else hedge_ms / 1e3)
            else:
                recv_t = timeout
            try:
                self._r_ensure_connected()
                cmd, payload = self._r_recv(recv_t)
            except socket.timeout:
                failures += 1
                if failures > limit:
                    err = TimeoutError(
                        f"{self.name}: no result within {recv_t:.3f}s "
                        f"after {failures - 1} recovery attempt(s)")
                    break
                if hedging:
                    _res.metrics()["hedges"].inc()
                    if tl is not None:
                        tl.mark("net_hedge", None, track="net",
                                endpoint=str(self._r_endpoint))
                    self._server_idx += 1  # fail over to the next replica
                    self.log.warning("hedge timer (%.3fs) fired; failing "
                                     "over to the next replica", recv_t)
                else:
                    self.log.warning("recv timed out after %.3fs; "
                                     "reconnecting", recv_t)
                self._kill_sock()
                continue
            except (OSError, P.QueryProtocolError) as e:
                failures += 1
                self._r_conn_failure(e)
                if failures > limit:
                    err = e
                    break
                continue
            if cmd is P.Cmd.RESULT_EX:
                req_id, _slack, body = P.unpack_ext(payload)
                entry = self._r_pop_pending(req_id)
                if entry is None:
                    continue  # dedup replay of an already-delivered result
                if entry.sent_t and entry.endpoint is not None:
                    self._r_stat(*entry.endpoint).observe(
                        time.monotonic() - entry.sent_t)
                done.append((P.unpack_buffer(body), entry))
                failures = 0
            elif cmd is P.Cmd.RESULT_EX2:
                req_id, _slack, _tid, _stamp, blob, body = \
                    P.unpack_ext2(payload)
                entry = self._r_pop_pending(req_id)
                if entry is None:
                    continue  # dedup replay of an already-delivered result
                now = time.monotonic()
                if entry.sent_t:
                    if entry.endpoint is not None:
                        self._r_stat(*entry.endpoint).observe(
                            now - entry.sent_t)
                    # splice the remote span vector into this frame's
                    # ledger, anchored inside our own RTT window
                    _dist.splice_remote(
                        tl, entry.meta.get(_timeline.TRACE_SEQ_META),
                        entry.sent_t, now, entry.sent_wall,
                        _dist.unpack_span_blob(blob))
                done.append((P.unpack_buffer(body), entry))
                failures = 0
            elif cmd is P.Cmd.EXPIRED:
                req_id, _slack, _body = P.unpack_ext(payload)
                entry = self._r_pop_pending(req_id)
                if entry is not None:
                    self._props["frames_expired"] = \
                        int(self._props.get("frames_expired", 0)) + 1
                    if tl is not None:
                        tl.mark("net_expired",
                                entry.meta.get(_timeline.TRACE_SEQ_META),
                                track="net", req_id=req_id)
                    self.log.info("frame pts=%s expired remotely "
                                  "(req %d)", entry.pts, req_id)
                failures = 0
            elif cmd is P.Cmd.PING:
                continue
            else:
                failures += 1
                self._r_conn_failure(P.QueryProtocolError(
                    f"unexpected {cmd} in reliable mode"))
                if failures > limit:
                    err = P.QueryProtocolError(
                        f"unexpected {cmd} in reliable mode")
                    break
        if err is not None:
            n = self._drop_pending_locked()
            if n:
                self.log.warning("reliable transport exhausted (%s); "
                                 "dropped %d frame(s)", err, n)
        return done, err

    def _chain_resilient(self, buf):
        if self._refwire():
            raise FlowError(
                "tensor_query_client: reliable=true requires wire=nnstpu")
        window = max(1, int(self.get_property("max_in_flight")))
        with self._lock:
            entry = self._r_make_entry(buf)
            self._r_transmit(entry)
            done, err = self._r_drain_locked(min_pending=window)
        ret = FlowReturn.OK
        for result, done_entry in done:
            ret = self._push_result(result, done_entry.pts,
                                    done_entry.meta)
        if err is not None:
            raise err  # after pushing the good results collected so far
        return ret

    # -- fleet balancing (query/balance.py) ---------------------------------
    def _balance_on(self) -> bool:
        """True when the shortest-slack balancer owns this client's
        routing. ``balance=off`` (default) and the ``NNSTPU_FLEET=0``
        kill switch both leave the classic single-connection paths
        untouched — no balance state is ever created."""
        mode = str(self.get_property("balance") or _bal.MODE_OFF)
        if mode in ("", _bal.MODE_OFF):
            return False
        if os.environ.get("NNSTPU_FLEET", "").strip() == "0":
            return False
        if mode != _bal.MODE_SHORTEST_SLACK:
            raise FlowError(
                f"tensor_query_client: unknown balance mode {mode!r} "
                f"(off | shortest-slack)")
        return True

    def _b_server_list(self) -> List[Tuple[str, int]]:
        """Candidate endpoints, refreshed per route. With an operation,
        the discovery subscription is kept open (unlike the classic
        per-connect lookup) so refreshed ads keep delivering fresh load
        blocks and stale replicas age out mid-stream."""
        operation = self.get_property("operation")
        if not operation:
            return self._server_list()  # static servers=/host:port list
        if self._b_discovery is None:
            from nnstreamer_tpu.query.discovery import ServerDiscovery

            stale = float(self.get_property("discovery_stale_s") or 0.0)
            self._b_discovery = ServerDiscovery(
                self.get_property("broker_host"),
                int(self.get_property("broker_port")),
                str(operation), stale_s=stale if stale > 0 else None)
            return self._b_discovery.wait_servers(
                timeout=float(self.get_property("timeout")))
        found = self._b_discovery.servers_now()
        if not found:
            found = self._b_discovery.wait_servers(
                timeout=float(self.get_property("timeout")))
        return found

    def _b_channel(self, endpoint: Tuple[str, int]) -> _BChannel:
        ch = self._b_channels.get(endpoint)
        if ch is None:
            ch = self._b_channels[endpoint] = _BChannel(endpoint)
        return ch

    def _b_candidates(self, exclude=()):
        """(endpoint, rtt, inflight, load) rows for the policy ranking —
        breaker-open endpoints excluded here (the policy stays pure)."""
        cands = []
        for host, port in self._b_server_list():
            ep = (host, port)
            if ep in exclude:
                continue
            if not self._r_breaker(host, port).allow():
                continue
            ch = self._b_channels.get(ep)
            raw = self._b_discovery.load(host, port) \
                if self._b_discovery is not None else None
            load = _bal.parse_ad_load({"load": raw}) if raw else None
            cands.append((ep, self._r_stat(host, port).ewma(),
                          len(ch.pending) if ch is not None else 0, load))
        return cands

    def _b_ensure_channel(self, ch: _BChannel) -> None:
        if ch.sock is not None:
            return
        sock, _cid = self._open_nnstpu(*ch.endpoint)
        try:
            ch.dt1 = self._hello_on(sock)
        except (OSError, P.QueryProtocolError):
            try:
                sock.close()
            except OSError:
                pass
            raise
        ch.sock = sock

    def _b_channel_failure(self, ch: _BChannel, err: Exception) -> None:
        backoff_s = float(self.get_property("reconnect_backoff_ms")
                          or 50.0) / 1e3
        self._r_breaker(*ch.endpoint).record_failure()
        ch.kill()
        ch.failures += 1
        ch.next_attempt_t = time.monotonic() + min(
            2.0, backoff_s * (2 ** min(ch.failures - 1, 6)))
        self.log.warning("fleet channel %s:%d error: %s (failure %d)",
                         ch.endpoint[0], ch.endpoint[1], err, ch.failures)

    def _b_route(self, entry: _res.PendingEntry, exclude=()) -> None:
        """Send one entry to the best-scoring endpoint, walking the
        ranking (then backing off and re-resolving the server list) on
        failure. Raises only when no replica accepts the frame within
        ``max_retry`` rounds."""
        policy = _res.RetryPolicy(
            base_ms=float(self.get_property("reconnect_backoff_ms")
                          or 50.0),
            key=f"{self.name}:lb")
        limit = max(1, int(self.get_property("max_retry")))
        last_err: Optional[Exception] = None
        for attempt in range(1, limit + 1):
            ranked = _bal.rank(self._b_candidates(exclude=exclude))
            if not ranked and exclude:
                # every sibling is breaker-open or gone — the excluded
                # (draining) endpoint beats dropping the frame
                ranked = _bal.rank(self._b_candidates())
            for sc, ep in ranked:
                ch = self._b_channel(ep)
                if ch.sock is None and \
                        time.monotonic() < ch.next_attempt_t:
                    continue  # endpoint still in reconnect backoff
                try:
                    self._b_ensure_channel(ch)
                    self._r_send_entry(entry, ch=ch)
                except (OSError, P.QueryProtocolError) as e:
                    last_err = e
                    self._b_channel_failure(ch, e)
                    continue
                self._r_breaker(*ep).record_success()
                ch.pending.append(entry)
                self._b_pending[entry.req_id] = entry
                _bal.note_route(ep, sc)
                return
            policy.sleep(attempt)
        raise P.QueryProtocolError(
            f"fleet: no replica accepted req {entry.req_id}: {last_err}")

    def _b_recv(self, ch: _BChannel, timeout: float):
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("query.recv")
            if act == "disconnect":
                ch.kill()
                raise OSError("injected fault: query.recv disconnect")
            if act is not None:
                raise P.QueryProtocolError(
                    f"injected fault: query.recv {act}")
        ch.sock.settimeout(max(0.001, timeout))
        return P.recv_msg(ch.sock)

    def _b_pop(self, req_id: int) -> Optional[_res.PendingEntry]:
        """Claim a completed request id — None for a duplicate (the
        hedged twin already answered; ignore, exactly-once holds)."""
        entry = self._b_pending.pop(req_id, None)
        if entry is None:
            return None
        for other in self._b_channels.values():
            for i, e in enumerate(other.pending):
                if e.req_id == req_id:
                    other.pending.pop(i)
                    break
        return entry

    def _b_observe(self, ch: _BChannel, entry: _res.PendingEntry,
                   now: float) -> None:
        ep = entry.endpoint or ch.endpoint
        self._r_stat(*ep).observe(now - entry.sent_t)

    def _b_handle_msg(self, ch: _BChannel, cmd, payload) -> bool:
        """Apply one received message; True when it completed a frame."""
        tl = _timeline.ACTIVE
        if cmd is P.Cmd.RESULT_EX:
            req_id, _slack, body = P.unpack_ext(payload)
            entry = self._b_pop(req_id)
            if entry is None:
                return False  # dedup replay of a delivered result
            if entry.sent_t:
                self._b_observe(ch, entry, time.monotonic())
            self._b_results[req_id] = (P.unpack_buffer(body), entry)
            ch.failures = 0
            return True
        if cmd is P.Cmd.RESULT_EX2:
            req_id, _slack, _tid, _stamp, blob, body = \
                P.unpack_ext2(payload)
            entry = self._b_pop(req_id)
            if entry is None:
                return False  # dedup replay of a delivered result
            now = time.monotonic()
            if entry.sent_t:
                self._b_observe(ch, entry, now)
                _dist.splice_remote(
                    tl, entry.meta.get(_timeline.TRACE_SEQ_META),
                    entry.sent_t, now, entry.sent_wall,
                    _dist.unpack_span_blob(blob))
            self._b_results[req_id] = (P.unpack_buffer(body), entry)
            ch.failures = 0
            return True
        if cmd is P.Cmd.EXPIRED:
            req_id, _slack, _body = P.unpack_ext(payload)
            entry = self._b_pop(req_id)
            ch.failures = 0
            if entry is None:
                return False
            self._b_done_ids.add(req_id)
            self._props["frames_expired"] = \
                int(self._props.get("frames_expired", 0)) + 1
            if tl is not None:
                tl.mark("net_expired",
                        entry.meta.get(_timeline.TRACE_SEQ_META),
                        track="net", req_id=req_id)
            self.log.info("frame pts=%s expired remotely (req %d)",
                          entry.pts, req_id)
            return True
        if cmd is P.Cmd.PING:
            return False
        self._b_channel_failure(ch, P.QueryProtocolError(
            f"unexpected {cmd} in balance mode"))
        return False

    def _b_stall_timeout(self, ch: _BChannel) -> float:
        hedge_ms = float(self.get_property("hedge_ms") or 0.0)
        if hedge_ms > 0.0:
            return self._r_stat(*ch.endpoint).hedge_timeout(
                hedge_ms / 1e3)
        return float(self.get_property("timeout"))

    def _b_check_channels(self) -> None:
        """Recovery pass: stalled live channels are killed (their next
        pass reconnects), dead channels reconnect sticky and resend, and
        a channel past ``max_retry`` failures hedges its survivors to
        sibling replicas."""
        limit = max(1, int(self.get_property("max_retry")))
        m = _res.metrics()
        for ch in list(self._b_channels.values()):
            if not ch.pending:
                continue
            now = time.monotonic()
            if ch.sock is not None:
                oldest = min((e.sent_t for e in ch.pending if e.sent_t),
                             default=0.0)
                stall_t = self._b_stall_timeout(ch)
                if oldest and now - oldest > stall_t:
                    self._b_channel_failure(ch, TimeoutError(
                        f"no result within {stall_t:.3f}s"))
                continue
            if ch.failures > limit:
                entries, ch.pending = ch.pending, []
                ch.failures = 0  # fresh slate if the endpoint returns
                for e in entries:
                    m["hedges"].inc()
                    _bal.lb_metrics()["reroutes"].inc()
                    try:
                        self._b_route(e, exclude=(ch.endpoint,))
                    except P.QueryProtocolError:
                        # honest last resort: account the frame dropped
                        self._b_pending.pop(e.req_id, None)
                        self._b_done_ids.add(e.req_id)
                        self._props["frames_dropped"] = \
                            int(self._props.get("frames_dropped", 0)) + 1
                continue
            if now < ch.next_attempt_t:
                continue
            try:
                self._b_ensure_channel(ch)
                for e in ch.pending:  # sticky resend, in send order
                    self._r_send_entry(e, ch=ch)
                    m["retries"].inc()
            except (OSError, P.QueryProtocolError) as e:
                self._b_channel_failure(ch, e)

    def _b_flush_ready(self) -> List[tuple]:
        """The in-order deliverable prefix: results release downstream
        strictly in send order, so balance mode keeps the classic
        single-connection ordering contract across N channels."""
        out: List[tuple] = []
        while self._b_deliver_next is not None:
            rid = self._b_deliver_next
            got = self._b_results.pop(rid, None)  # atomic claim
            if got is not None:
                out.append(got)
            elif rid in self._b_done_ids or (
                    rid < self._r_next_id
                    and rid not in self._b_pending):
                # expired/dropped (or gone without a trace) — skip it
                # rather than wedge the stream; discard is a no-op for
                # ids that were never in the done set
                self._b_done_ids.discard(rid)
            else:
                break
            self._b_deliver_next = rid + 1
        return out

    def _b_drain_locked(self, min_pending: int):
        """Receive across every live channel until fewer than
        ``min_pending`` frames remain in flight (caller holds the lock).
        Returns ``(done, err)`` with ``done`` the in-order deliverable
        prefix; ``err`` reports exhaustion after the whole fleet made no
        progress for ``timeout * (max_retry + 1)``, with the remaining
        frames dropped and counted (the honest last resort)."""
        err: Optional[Exception] = None
        timeout = float(self.get_property("timeout"))
        limit = max(1, int(self.get_property("max_retry")))
        deadline = time.monotonic() + timeout * (limit + 1)
        while len(self._b_pending) >= min_pending:
            socks = {ch.sock: ch for ch in self._b_channels.values()
                     if ch.sock is not None and ch.pending}
            progress = False
            if socks:
                try:
                    readable, _, _ = select.select(
                        list(socks), [], [], 0.02)
                except (OSError, ValueError):
                    readable = []  # a racing close invalidated an fd
                for s in readable:
                    ch = socks[s]
                    try:
                        cmd, payload = self._b_recv(ch, timeout)
                    except (socket.timeout, OSError,
                            P.QueryProtocolError) as e:
                        self._b_channel_failure(ch, e)
                        continue
                    if self._b_handle_msg(ch, cmd, payload):
                        progress = True
            self._b_check_channels()
            if progress:
                deadline = time.monotonic() + timeout * (limit + 1)
            else:
                if time.monotonic() > deadline:
                    err = TimeoutError(
                        f"{self.name}: fleet made no progress within "
                        f"{timeout * (limit + 1):.1f}s "
                        f"({len(self._b_pending)} frame(s) in flight)")
                    for rid in list(self._b_pending):
                        self._b_pop(rid)
                        self._b_done_ids.add(rid)
                        self._props["frames_dropped"] = \
                            int(self._props.get("frames_dropped", 0)) + 1
                    break
                if not socks:
                    time.sleep(0.01)  # whole fleet down: wait on backoff
        return self._b_flush_ready(), err

    def _chain_balanced(self, buf):
        if self._refwire():
            raise FlowError(
                "tensor_query_client: balance requires wire=nnstpu")
        window = max(1, int(self.get_property("max_in_flight")))
        with self._lock:
            entry = self._r_make_entry(buf)
            if self._b_deliver_next is None:
                self._b_deliver_next = entry.req_id
            self._b_route(entry)
            done, err = self._b_drain_locked(min_pending=window)
        ret = FlowReturn.OK
        for result, done_entry in done:
            ret = self._push_result(result, done_entry.pts,
                                    done_entry.meta)
        if err is not None:
            raise err  # after pushing the good results collected so far
        return ret

    def chain(self, pad, buf):
        if self.get_property("reliable"):
            if self._balance_on():
                return self._chain_balanced(buf)
            return self._chain_resilient(buf)
        if self._balance_on():
            raise FlowError(
                "tensor_query_client: balance=shortest-slack requires "
                "reliable=true (request ids + the server dedup window "
                "are what make re-routed frames exactly-once)")
        window = max(1, int(self.get_property("max_in_flight")))
        if window == 1:
            # synchronous round trip with per-frame resend on reconnect
            with self._lock:
                for attempt in (1, 2):  # one transparent reconnect per frame
                    if self._sock is None:
                        self._connect()
                    try:
                        self._send_buf(buf)
                        result = self._recv_result()
                        break
                    except (OSError, P.QueryProtocolError) as e:
                        self.log.warning("query round-trip failed: %s", e)
                        self._disconnect_locked()
                        if attempt == 2:
                            raise
            return self._push_result(result, buf.pts, buf.meta)

        # pipelined: keep up to `window` requests in flight; responses
        # arrive in order on the same connection. A frame that cannot be
        # SENT (server unreachable) errors like the sync path; frames
        # already in flight when the connection dies are dropped (streaming
        # frame-drop semantics).
        done = []
        with self._lock:
            for attempt in (1, 2):  # one transparent reconnect per frame
                if self._sock is None:
                    self._connect()
                try:
                    self._send_buf(buf)
                    self._pending.append((buf.pts, buf.meta))
                    break
                except (OSError, P.QueryProtocolError) as e:
                    n = self._drop_pending_locked()
                    self.log.warning("pipelined send failed: %s; dropped %d "
                                     "in-flight frame(s)", e, n)
                    self._disconnect_locked()
                    if attempt == 2:
                        raise
            done, err = self._drain_locked(min_pending=window)
        ret = FlowReturn.OK
        for result, pts, meta in done:
            ret = self._push_result(result, pts, meta)
        if err is not None:
            raise err  # after pushing the good results collected so far
        return ret

    def _drain_locked(self, min_pending: int):
        """Receive results until fewer than ``min_pending`` remain in
        flight (caller holds the lock). Returns ``(done, err)`` — results
        successfully received before any failure are always returned so
        the caller can push them. ``err`` is a TimeoutError when a healthy
        connection stopped answering (must surface as a pipeline error,
        not as silently vanishing frames); a broken connection just drops
        the in-flight frames (streaming semantics)."""
        done = []
        err = None
        try:
            while len(self._pending) >= min_pending and \
                    self._sock is not None:
                result = self._recv_result()
                pts, meta = self._pending.pop(0)
                done.append((result, pts, meta))
        except TimeoutError as e:
            self._drop_pending_locked()
            self._disconnect_locked()
            err = e
        except (OSError, P.QueryProtocolError) as e:
            n = self._drop_pending_locked()
            self.log.warning("pipelined receive failed (%s); dropped %d "
                             "in-flight frame(s)", e, n)
            self._disconnect_locked()
        return done, err

    def handle_eos(self):
        """Receive every outstanding pipelined result before EOS forwards.

        A drain timeout is POSTED to the bus rather than raised: the EOS
        sentinel travels paths (e.g. queue worker threads) that do not
        wrap handlers in try/except, so a raise here could kill a worker
        silently instead of failing the pipeline."""
        if self.get_property("reliable"):
            with self._lock:
                if self._balance_on():
                    done, err = self._b_drain_locked(min_pending=1)
                else:
                    done, err = self._r_drain_locked(min_pending=1)
            for result, entry in done:
                self._push_result(result, entry.pts, entry.meta)
            if err is not None:
                self.post_error(FlowError(f"{self.name}: {err}"))
            return
        with self._lock:
            done, err = self._drain_locked(min_pending=1)
        for result, pts, meta in done:
            self._push_result(result, pts, meta)
        if err is not None:
            self.post_error(FlowError(f"{self.name}: {err}"))


@subplugin(ELEMENT, "tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    """Server-side source: accepts client connections and yields received
    buffers (client id attached as meta for serversink routing)."""

    ELEMENT_NAME = "tensor_query_serversrc"
    PROPERTIES = {
        **SourceElement.PROPERTIES,
        "host": "0.0.0.0",
        "port": 3000,
        "id": 0,  # pairs serversrc/serversink (reference `id` property)
        "num_buffers": -1,
        # broker advertising (reference query-hybrid server side)
        "operation": None,
        "broker_host": "127.0.0.1",
        "broker_port": 1883,
        "advertise_host": "127.0.0.1",
        # "nnstreamer" speaks the reference's raw-struct query wire on
        # TWO ports (src=port, sink=sink-port) so unmodified reference
        # clients can offload to this server (query/refwire.py)
        "wire": "nnstpu",
        "sink_port": 0,
        # refwire carries no per-tensor meta: a caps string here (e.g.
        # "other/tensors,num_tensors=1,dimensions=3:4,types=float32")
        # reconstructs typed tensors from the raw mems and is announced
        # to clients in the APPROVE reply
        "caps": None,
        # accept the resilient extension (HELLO/TRANSFER_EX): per-client
        # dedup windows, deadline admission, EXPIRED notices. Forces the
        # pure-Python transport (the native epoll core only speaks the
        # classic commands); leave false for byte-identical classic wire
        "reliable": False,
        # where this replica's MetricsServer /metrics.json lives —
        # advertised through the broker so fleet federation
        # (obs/distributed.py) can discover its scrape targets
        "metrics_port": 0,
        # > 0: re-publish the discovery ad on this cadence, each refresh
        # carrying a live load block (ingress depth + SLO-scheduler slack)
        # for shortest-slack clients; 0 keeps the classic publish-once ad
        "advertise_interval_s": 0.0,
    }

    _SERVERS = {}
    _SERVERS_LOCK = threading.Lock()

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.server: Optional[QueryServer] = None
        self.i = 0
        self._advertiser = None

    def start(self):
        super().start()
        self.server = QueryServer(
            host=self.get_property("host"),
            port=int(self.get_property("port")),
            caps_str=str(self.get_property("caps") or ""),
            wire=str(self.get_property("wire")),
            sink_port=int(self.get_property("sink_port") or 0),
            resilient=bool(self.get_property("reliable")),
        ).start()
        with self._SERVERS_LOCK:
            self._SERVERS[int(self.get_property("id"))] = self.server
        operation = self.get_property("operation")
        if operation:
            from nnstreamer_tpu.query.discovery import ServerAdvertiser

            refresh_s = float(
                self.get_property("advertise_interval_s") or 0.0)
            self._advertiser = ServerAdvertiser(
                self.get_property("broker_host"),
                int(self.get_property("broker_port")),
                str(operation),
                self.get_property("advertise_host"),
                self.server.port,
                metrics_port=int(self.get_property("metrics_port") or 0),
                load_fn=self._ad_load if refresh_s > 0 else None,
                refresh_s=refresh_s,
            )
            self._advertiser.publish()

    def _ad_load(self) -> Optional[dict]:
        """Live load block for the refreshed discovery ad: ingress queue
        depth, plus the SLO scheduler's service estimate and the slack a
        newly admitted frame would have left (budget minus the expected
        wait behind the queued work). Scheduler-less replicas advertise
        depth alone — the balancer treats missing fields as unknown."""
        server = self.server
        if server is None:
            return None
        depth = int(server.incoming.qsize())
        load: dict = {"queue_depth": depth}
        sched = getattr(self.pipeline, "_slo_scheduler", None) \
            if self.pipeline is not None else None
        if sched is not None:
            snap = sched.snapshot()
            svc = float(snap.get("service_time_ms") or 0.0)
            budget = float(snap.get("budget_ms") or 0.0)
            if svc > 0.0:
                load["service_ms"] = svc
                if budget > 0.0:
                    load["slack_headroom_ms"] = \
                        budget - (depth + 1) * svc
        return load

    def stop(self):
        if self._advertiser is not None:
            try:
                self._advertiser.retract()
            except OSError:
                pass
            self._advertiser = None
        if self.server is not None:
            self.server.stop()
            with self._SERVERS_LOCK:
                self._SERVERS.pop(int(self.get_property("id")), None)
            self.server = None
        super().stop()

    @classmethod
    def get_server(cls, pair_id: int) -> Optional[QueryServer]:
        with cls._SERVERS_LOCK:
            return cls._SERVERS.get(pair_id)

    @property
    def port(self) -> int:
        """Bound port (use port=0 to pick a free one in tests)."""
        return self.server.port if self.server else \
            int(self.get_property("port"))

    @property
    def result_port(self) -> int:
        """Refwire sink (result) port once bound."""
        return self.server.sink_port if self.server else \
            int(self.get_property("sink_port"))

    def negotiate(self):
        caps_prop = self.get_property("caps")
        if caps_prop:
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            self.srcpad.set_caps(parse_caps_string(str(caps_prop)))
            return
        self.srcpad.set_caps(
            TensorsConfig(format=TensorFormat.FLEXIBLE).to_caps()
        )

    def create(self):
        n = int(self.get_property("num_buffers"))
        if 0 <= n <= self.i:
            return None
        while not self._stop_evt.is_set():
            server = self.server  # stop() nulls the attribute concurrently
            if server is None:
                return None
            buf = server.get_buffer(timeout=0.1)
            if buf is not None:
                self.i += 1
                return buf
        return None


@subplugin(ELEMENT, "tensor_query_serversink")
class TensorQueryServerSink(Element):
    """Server-side sink: returns each result to the client that sent the
    corresponding input (routing by query_client_id meta — the reference's
    GstMetaQuery client-id routing)."""

    ELEMENT_NAME = "tensor_query_serversink"
    PROPERTIES = {**Element.PROPERTIES, "id": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")

    def chain(self, pad, buf):
        server = TensorQueryServerSrc.get_server(int(self.get_property("id")))
        if server is None:
            raise RuntimeError(
                "tensor_query_serversink: no paired serversrc (check `id`)"
            )
        client_id = buf.meta.get("query_client_id")
        if client_id is None:
            raise RuntimeError(
                "tensor_query_serversink: buffer lost its query_client_id "
                "meta (keep meta intact through the server pipeline)"
            )
        server.send_result(int(client_id), buf)
        return FlowReturn.OK
