"""``nns-lint`` — static checks for pipelines and project invariants.

Usage::

    nns-lint "videotestsrc ! tensor_converter ! tensor_sink"
    nns-lint -f pipeline.txt
    nns-lint --self                       # AST lint the package itself
    nns-lint --concurrency                # whole-program NNS2xx pass
    nns-lint --scan examples/ docs/       # verify shipped descriptions
    nns-lint --format json "..."          # machine-readable output

Exit status: 0 when no error-severity diagnostics were found, 1 when
there were (or any warnings under ``--strict``), 2 on usage errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from nnstreamer_tpu.analysis.diagnostics import (
    ERROR,
    WARNING,
    Diagnostic,
    render_json,
    render_text,
)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nns-lint",
        description="Static pipeline verifier and project AST lint.")
    p.add_argument("description", nargs="?",
                   help="nns-launch pipeline description to verify")
    p.add_argument("-f", "--file", metavar="PATH",
                   help="read the pipeline description from a file")
    p.add_argument("--self", dest="lint_self", action="store_true",
                   help="run the project AST lint over the "
                        "nnstreamer_tpu package")
    p.add_argument("--concurrency", action="store_true",
                   help="run the whole-program concurrency analysis "
                        "(NNS2xx: guarded attributes, lock ordering, "
                        "check-then-act, foreign calls under lock) over "
                        "the nnstreamer_tpu package")
    p.add_argument("--scan", nargs="+", metavar="PATH",
                   help="extract and verify pipeline descriptions from "
                        "python/markdown files or directories")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--strict", action="store_true",
                   help="treat warnings as failures")
    return p


def _scan_paths(paths: List[str]) -> List[Diagnostic]:
    from nnstreamer_tpu.analysis.extract import extract_from_file
    from nnstreamer_tpu.analysis.verify import verify_description

    diags: List[Diagnostic] = []
    for raw in paths:
        path = Path(raw)
        files = sorted(p for ext in ("*.py", "*.md")
                       for p in path.rglob(ext)) if path.is_dir() \
            else [path]
        for f in files:
            if "__pycache__" in f.parts:
                continue
            for snip in extract_from_file(f):
                diags.extend(verify_description(
                    snip.description,
                    source=f"{snip.source}:{snip.line}"))
    return diags


def main(argv=None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)

    modes = sum((bool(args.description or args.file), args.lint_self,
                 args.concurrency, bool(args.scan)))
    if modes == 0:
        parser.print_usage(sys.stderr)
        print("nns-lint: give a description, -f FILE, --self, "
              "--concurrency, or --scan", file=sys.stderr)
        return 2
    if args.description and args.file:
        print("nns-lint: give either a description or -f, not both",
              file=sys.stderr)
        return 2

    diags: List[Diagnostic] = []
    if args.description or args.file:
        from nnstreamer_tpu.analysis.verify import verify_description

        if args.file:
            try:
                text = Path(args.file).read_text(encoding="utf-8")
            except OSError as e:
                print(f"nns-lint: cannot read {args.file}: {e}",
                      file=sys.stderr)
                return 2
            diags.extend(verify_description(text, source=args.file))
        else:
            diags.extend(verify_description(args.description))
    if args.lint_self:
        from nnstreamer_tpu.analysis.astlint import lint_tree

        pkg_root = Path(__file__).resolve().parent.parent
        diags.extend(lint_tree(pkg_root))
    if args.concurrency:
        from nnstreamer_tpu.analysis.concurrency import lint_concurrency

        pkg_root = Path(__file__).resolve().parent.parent
        diags.extend(lint_concurrency(pkg_root))
    if args.scan:
        diags.extend(_scan_paths(args.scan))

    if args.format == "json":
        print(render_json(diags))
    else:
        print(render_text(diags))

    failing = {ERROR, WARNING} if args.strict else {ERROR}
    return 1 if any(d.severity in failing for d in diags) else 0


if __name__ == "__main__":
    sys.exit(main())
