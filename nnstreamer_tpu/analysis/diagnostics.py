"""Diagnostics model shared by the pipeline verifier and the AST lint.

One value type for every finding (``Diagnostic``: code, severity,
location, message, hint) so both halves of ``nns-lint`` — the static
pipeline verifier (``NNS0xx``) and the project-invariant AST rules
(``NNS1xx``) — render through the same text and JSON writers and gate CI
through the same exit-code policy. The shape mirrors what compiler-first
stream checkers emit (one record per finding, machine-readable), which is
what lets the CI job and ``tests/test_static_gates.py`` consume the same
output.

JSON schema (documented in ``docs/linting.md``; ``version`` bumps on any
incompatible change)::

    {"version": 1,
     "diagnostics": [{"code": "NNS001", "severity": "error",
                      "message": "...", "hint": "..." | null,
                      "loc": {"source": "...", "line": 1, "column": 37}}],
     "summary": {"error": N, "warning": N, "info": N}}
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEV_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

#: every diagnostic code with its one-line meaning — the table rendered in
#: docs/linting.md. NNS0xx: pipeline-graph findings; NNS1xx: AST rules;
#: NNS2xx: whole-program concurrency analysis.
CODE_TABLE: Dict[str, str] = {
    # -- graph (static pipeline verifier) ------------------------------------
    "NNS001": "unknown element factory",
    "NNS002": "unknown element property",
    "NNS003": "duplicate element name",
    "NNS004": "unknown element/pad reference",
    "NNS005": "empty caps intersection on a link (format mismatch)",
    "NNS006": "dangling pad (unlinked input, or dropped output)",
    "NNS007": "cycle in the pipeline graph",
    "NNS008": "mux/merge sync-policy conflict",
    "NNS009": "tee fan-out without queue (serialization/deadlock risk)",
    "NNS010": "leaky queue without drop monitoring",
    "NNS011": "unknown tensor_filter framework / subplugin",
    "NNS012": "description syntax error",
    # -- code (project-invariant AST lint) -----------------------------------
    "NNS101": "wall-clock time.time() where monotonic is required",
    "NNS102": "blocking call (sleep/join/socket IO) while holding a lock",
    "NNS103": "print() in library code (use log.py)",
    "NNS104": "bare or blind except (silently swallowed broad exception)",
    "NNS105": "thread created without an explicit daemon= choice",
    "NNS106": "metric name violates the nns_<subsystem>_ convention",
    "NNS107": "sync-forcing call in a per-frame hot path (defeats the "
              "dispatch window)",
    "NNS108": "direct tensor materialization outside the sanctioned "
              "to_host() site (bypasses the DeviceBuffer cache and the "
              "transfer counters)",
    "NNS109": "REORDER_SAFE class whose per-frame chain mutates self "
              "state (lane clones would diverge from the serial element)",
    "NNS110": "blocking sleep or unbounded wait in a scheduler/dispatch "
              "hot path (stales admission decisions, wedges EOS)",
    "NNS111": "broad except in an element chain/worker loop that "
              "neither re-raises nor posts to the pipeline bus (a dead "
              "frame becomes a silent hang)",
    "NNS112": "socket/channel send-recv in a transport hot path without "
              "an explicit timeout (a dead peer hangs the path instead "
              "of feeding the retry/hedge/breaker machinery)",
    "NNS113": "direct jax.device_put outside the HBM budget accountant's "
              "tracked entry points (bytes land in device memory that "
              "nns_mem_used_bytes never sees, so the pressure ladder "
              "runs on an undercount)",
    "NNS114": "unbounded list.append/deque() without maxlen in an obs "
              "hot-path recording function (always-on telemetry records "
              "on every frame for the process lifetime — an unbounded "
              "container there is a slow leak)",
    "NNS115": "checkpoint save/load key-set drift: a snapshot/restore or "
              "checkpoint_state/restore_state pair whose literal state "
              "keys disagree (a saved key the load never reads is dead "
              "state; a read key the save never writes is absent on "
              "every real restore)",
    "NNS116": "wire-header struct format vs pack/unpack site field-count "
              "disagreement (a NAME.pack(...) passing the wrong number "
              "of values, or a tuple-unpack binding the wrong number of "
              "names, raises only at runtime — on the first real frame, "
              "usually on the peer)",
    "NNS117": "GSPMD sharding constructed outside the parallel package "
              "(NamedSharding/PositionalSharding/shard_map/pjit anywhere "
              "else scatters placement decisions that parallel/serve.py "
              "keeps auditable — pass a mesh spec or plan instead)",
    "NNS118": "direct subscript of a paged KV arena outside "
              "serving/kvpool.py (block refcounts, buffer donation, and "
              "the zero-block/sentinel invariants live in the pool; a "
              "raw arena index elsewhere can read a freed block's stale "
              "bytes or write through a donated buffer)",
    "NNS119": "hard-coded host:port endpoint literal outside "
              "query/discovery.py, config modules, and tests (fleet "
              "replicas bind ephemeral ports and move at every deploy — "
              "a baked-in endpoint pins code to one replica and "
              "bypasses discovery, the breaker, and the balancer)",
    "NNS199": "nns-lint pragma without a justification",
    # -- concurrency (whole-program analysis) --------------------------------
    "NNS201": "access to a lock-guarded attribute outside the lock (the "
              "class mutates it under its lock everywhere else, so the "
              "unguarded access races every locked reader/writer)",
    "NNS202": "lock-order cycle in the project-wide acquisition graph "
              "(two threads taking the same locks in opposite orders "
              "deadlock), or a non-reentrant lock re-acquired while held",
    "NNS203": "check-then-act race: membership test and mutation of a "
              "lock-guarded container in separate critical sections "
              "(another thread can interleave between test and act)",
    "NNS204": "foreign call under lock: a callback/hook/fn-gauge or "
              "pipeline-bus post invoked while holding a subsystem lock "
              "(the callee may block or re-enter — the reentrancy-"
              "deadlock shape)",
}


@dataclasses.dataclass(frozen=True)
class Location:
    """Where a finding points: a source identifier plus 1-based line and
    column. For pipeline descriptions ``source`` is the file (or
    ``<description>``) and ``line`` is 1 unless the description came from
    a multi-line file."""

    source: str = "<description>"
    line: int = 1
    column: int = 1

    def __str__(self):
        return f"{self.source}:{self.line}:{self.column}"


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: stable code, severity, location, message, fix hint."""

    code: str
    severity: str          # ERROR | WARNING | INFO
    loc: Location
    message: str
    hint: Optional[str] = None

    def render(self) -> str:
        out = f"{self.loc}: {self.severity}: {self.code} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "hint": self.hint,
            "loc": {"source": self.loc.source, "line": self.loc.line,
                    "column": self.loc.column},
        }


def sort_diagnostics(diags: List[Diagnostic]) -> List[Diagnostic]:
    """Stable order: by source, line, column, then severity."""
    return sorted(diags, key=lambda d: (d.loc.source, d.loc.line,
                                        d.loc.column,
                                        _SEV_ORDER.get(d.severity, 9),
                                        d.code))


def summarize(diags: List[Diagnostic]) -> Dict[str, int]:
    out = {ERROR: 0, WARNING: 0, INFO: 0}
    for d in diags:
        out[d.severity] = out.get(d.severity, 0) + 1
    return out


def render_text(diags: List[Diagnostic]) -> str:
    diags = sort_diagnostics(diags)
    lines = [d.render() for d in diags]
    s = summarize(diags)
    lines.append(f"nns-lint: {s[ERROR]} error(s), {s[WARNING]} warning(s), "
                 f"{s[INFO]} info")
    return "\n".join(lines)


def render_json(diags: List[Diagnostic]) -> str:
    diags = sort_diagnostics(diags)
    return json.dumps(
        {"version": 1,
         "diagnostics": [d.to_json() for d in diags],
         "summary": summarize(diags)},
        indent=2)


def has_errors(diags: List[Diagnostic]) -> bool:
    return any(d.severity == ERROR for d in diags)
